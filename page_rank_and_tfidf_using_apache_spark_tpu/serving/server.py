"""Long-lived TF-IDF query server: warm compiled runners, padded
micro-batches, device-fused top-k, hot-query LRU cache (ISSUE 8), and —
since ISSUE 13 — impacted-list scoring over live delta segments.

Request lifecycle::

    submit(terms) ──► bounded queue ──► drain thread ──► LRU cache?
                                                 │ miss
                                                 ▼
                      pad to batch cap (grow_chunk_cap, min_bits=0)
                                                 ▼
            ┌─ scoring="coo":      ops.score_query_batch per segment
            └─ scoring="impacted": host planner slices each query term's
               posting run from the CSC-by-term offsets, pads the runs
               into fixed-width buckets, ONE ops.score_impacted_batch
               dispatch per segment — work ∝ Σ df(query terms), not nnz
                                                 ▼
                      >1 live segment: ops.topk_merge (device-side,
                      globalizes doc ids; only [B, k] crosses D2H)
                                                 ▼
                      guarded pull ──► per-request futures resolve

Design points, each load-bearing for the acceptance gates:

- **Finite batch-shape matrix.**  A micro-batch of ``b`` misses pads to
  ``grow_chunk_cap(b, 0, min_bits=0)`` — the next power of two — clipped
  by ``max_batch``, so the only batch shapes that ever reach jit are
  ``{1, 2, 4, ..., max_batch}``.  :func:`TfidfServer.warmup` compiles all
  of them up front; the ``tfidf_score_query_batch`` /
  ``tfidf_score_impacted_batch`` registry entries trace the same matrix,
  so tier-2 *proves* zero per-request recompiles.  The impacted path adds
  ONE more padded axis — the bucket count, carried pow2 like the ingest
  chunk cap (``grow_chunk_cap`` at ``IMPACT_MIN_BUCKET_BITS``) — so a
  heavier query stream bumps the cap with a logged recompile instead of
  compiling per shape.
- **Latency shape.**  ``scoring="impacted"`` makes served work
  proportional to the batch's query terms' posting runs: the host slices
  ``[start, len)`` runs from the artifact's ``term_offsets`` table and
  the device program is reshape → gather → scatter-add over ``C·W`` rows.
  Results are byte-equal to the full-COO path (pinned per ranker): the
  contributions arrive per (row, doc) in the same order segment_sum adds
  them, and pad slots add exact ``±0.0``.
- **Segments.**  The server holds N live segments (delta commits of the
  streaming ingest — serving/segments.py) and scores a batch across all
  of them with a device-side merge of per-segment top-k.
  :meth:`refresh_segments` hot-swaps the live set WITHOUT restart: the
  replacement device state is built and warmed first, then swapped under
  the cache lock; in-flight batches finish against the old (still live)
  buffers, the result cache is invalidated by generation.
- **Resilience.**  The dispatch and the pull run under the resilience
  executor (sites ``serve_dispatch`` / ``serve_pull``): transient faults
  retry invisibly; a persistent fault fails exactly the requests of the
  batch that hit it — the queue keeps draining (chaos-tested at
  ``serve_dispatch:fail@%5`` and a hard ``lost``).
- **Telemetry.**  Every batch is a ``serve.batch`` span with ``serve.pad``
  / ``serve.dispatch`` / ``serve.pull`` children; every request publishes
  a ``serve_request`` event carrying queue-wait and total latency, so
  ``tools/trace_report.py`` renders queue-wait vs pad vs dispatch vs pull
  and per-request p50/p99 from the artifact alone.
- **LRU.**  Results are cached under a hash of the *canonical* query
  vector (term-id-sorted, duplicate terms combined), so "foo bar" and
  "bar foo" hit the same entry; hits resolve on the drain thread without
  touching the device and publish ``serve.cache_hits`` counters.
"""

from __future__ import annotations

import collections
import dataclasses
import functools
import hashlib
import queue
import threading
import time
from typing import Sequence

import numpy as np

from page_rank_and_tfidf_using_apache_spark_tpu import obs
from page_rank_and_tfidf_using_apache_spark_tpu.io import text as tio
from page_rank_and_tfidf_using_apache_spark_tpu.models.tfidf import grow_chunk_cap
from page_rank_and_tfidf_using_apache_spark_tpu.ops import tfidf as ops
from page_rank_and_tfidf_using_apache_spark_tpu.resilience import executor as rx
from page_rank_and_tfidf_using_apache_spark_tpu.serving.artifact import ServableIndex
from page_rank_and_tfidf_using_apache_spark_tpu.serving.segments import (
    LoadedSegment,
    SegmentSet,
    wrap_index_as_set,
)
from page_rank_and_tfidf_using_apache_spark_tpu.utils.config import (
    TUNABLE_DEFAULTS,
    TfidfConfig,
)
from page_rank_and_tfidf_using_apache_spark_tpu.utils.metrics import MetricsRecorder

# Floor of the impacted-list bucket-count cap: the carried pow2 cap starts
# at 2**this and doubles on demand (a logged recompile), exactly the
# streaming chunk-cap policy at a serving-sized floor.
IMPACT_MIN_BUCKET_BITS = 6


@dataclasses.dataclass(frozen=True)
class ServeConfig:
    """Operational knobs of one server instance (semantics live in the
    index artifact's TfidfConfig — a server never re-interprets weights)."""

    top_k: int = 10
    # micro-batch cap; padded shapes are pow2 <= this
    max_batch: int = TUNABLE_DEFAULTS["max_batch"]
    max_query_terms: int = 16  # Q: fixed per-query sparse slot count
    queue_depth: int = 64  # bound on submitted-but-undrained requests
    flush_ms: float = 2.0  # how long the drain waits to fill a batch
    cache_size: int = 1024  # LRU entries (0 disables the result cache)
    rank_alpha: float = 0.0  # additive PageRank-prior scale (0 = off),
    # applied to EVERY request (the server-level blend)
    prior_alpha: float = 0.0  # per-REQUEST PageRank-prior scale: > 0
    # enables ranker="prior" (tfidf weights + prior_alpha * ranks for
    # exactly the requests that opt in); the prior rides as a traced
    # operand, so the compiled batch matrix is shared with tfidf/bm25
    scoring: str = "coo"  # "coo" (full-postings batch scatter/gather) or
    # "impacted" (CSC-by-term run slicing — work ∝ the query's terms'
    # posting runs; byte-equal results, latency-shaped cost)
    # fixed bucket width W the impacted planner pads posting runs to
    # (sort_shuffle's bucket trick)
    impact_bucket_width: int = TUNABLE_DEFAULTS["impact_bucket_width"]
    impact_warm_buckets: int = TUNABLE_DEFAULTS[
        "impact_warm_buckets"]  # ceiling on the bucket cap the
    # warmup PRE-GROWS to (sized from the live set's heaviest posting
    # runs): a cap bump is a recompile ON the serving path, so warmup
    # sizes the carried cap for the worst plausible batch up front —
    # runtime can still grow past this (logged), it just shouldn't have
    # to in steady state

    def __post_init__(self) -> None:
        if self.top_k < 1:
            raise ValueError(f"top_k must be >= 1, got {self.top_k}")
        if self.max_batch < 1:
            raise ValueError(f"max_batch must be >= 1, got {self.max_batch}")
        if self.max_query_terms < 1:
            raise ValueError(
                f"max_query_terms must be >= 1, got {self.max_query_terms}"
            )
        if self.queue_depth < 1:
            raise ValueError(f"queue_depth must be >= 1, got {self.queue_depth}")
        if self.cache_size < 0 or self.rank_alpha < 0 or self.prior_alpha < 0:
            raise ValueError(
                "cache_size, rank_alpha and prior_alpha must be >= 0"
            )
        if self.scoring not in ("coo", "impacted"):
            raise ValueError(
                f"scoring must be 'coo' or 'impacted', got {self.scoring!r}"
            )
        if self.impact_bucket_width < 2:
            raise ValueError(
                f"impact_bucket_width must be >= 2, got "
                f"{self.impact_bucket_width}"
            )
        if self.impact_warm_buckets < (1 << IMPACT_MIN_BUCKET_BITS):
            raise ValueError(
                f"impact_warm_buckets must be >= {1 << IMPACT_MIN_BUCKET_BITS}, "
                f"got {self.impact_warm_buckets}"
            )


def batch_cap(b: int, max_batch: int, metrics: MetricsRecorder) -> int:
    """The serving micro-batcher's padding policy: literally
    :func:`models.tfidf.grow_chunk_cap` with ``min_bits=0`` and no carried
    cap — a batch of ``b`` pads to the next power of two, clipped by
    ``max_batch``.  One policy, two call sites, one lint surface."""
    cap, _ = grow_chunk_cap(min(b, max_batch), 0, metrics, min_bits=0)
    return min(cap, max_batch)


def batch_shape_matrix(max_batch: int) -> list[int]:
    """Every padded batch size the policy can produce: the finite shape
    matrix warmup compiles and the tier-2 recompile gate traces."""
    caps: list[int] = []
    metrics = MetricsRecorder()
    for b in range(1, max_batch + 1):
        c = batch_cap(b, max_batch, metrics)
        if c not in caps:
            caps.append(c)
    return caps


def serve_pad_plan(
    batch_sizes: Sequence[int],
    max_batch: int = TUNABLE_DEFAULTS["max_batch"],
) -> list[tuple[str, float]]:
    """Static padding-waste plan of the serving micro-batcher: run raw
    batch sizes through the REAL :func:`batch_cap` policy and return
    ``[("serve", pad_frac)]`` — the tier-3 pad_frac surface for the
    batched query entry point, the serving counterpart of
    ``models.tfidf.stream_pad_plan``."""
    metrics = MetricsRecorder()
    total_raw = 0
    total_cap = 0
    for b in batch_sizes:
        total_raw += min(int(b), max_batch)
        total_cap += batch_cap(int(b), max_batch, metrics)
    pad_frac = (total_cap - total_raw) / max(total_cap, 1)
    return [("serve", pad_frac)]


def impacted_pad_plan(
    bucket_counts: Sequence[int], *, min_bits: int = IMPACT_MIN_BUCKET_BITS
) -> list[tuple[str, float]]:
    """Static padding-waste plan of the impacted-list bucket axis: raw
    per-batch bucket counts through the REAL carried grow_chunk_cap
    policy (pow2 floor ``2**min_bits``, doubling bumps) — the tier-3
    pad_frac surface for ``tfidf_score_impacted_batch``."""
    metrics = MetricsRecorder()
    cap = 0
    total_raw = 0
    total_cap = 0
    for n in bucket_counts:
        cap, _ = grow_chunk_cap(max(int(n), 1), cap, metrics,
                                min_bits=min_bits)
        total_raw += int(n)
        total_cap += cap
    pad_frac = (total_cap - total_raw) / max(total_cap, 1)
    return [("impacted", pad_frac)]


# "prior" scores with the tfidf weight table plus the per-request
# PageRank-prior blend (ServeConfig.prior_alpha) — the third traffic class
# of the soak's mixed workload.  All rankers share every compiled
# executable: the weight table AND the prior vector are traced operands.
RANKERS = ("tfidf", "bm25", "prior")


class ServerShutdown(RuntimeError):
    """The server stopped (graceful drain) while this request was in
    flight, or a submit arrived after stop().  Typed so callers — the
    fabric router rolling a replica, a soak client — can tell an orderly
    shutdown (re-dispatch elsewhere / re-submit) from a real serving
    failure: a stopped server never hangs a client, it fails fast with
    this."""


class _Pending:
    """One in-flight request: a tiny future the drain thread resolves."""

    __slots__ = ("key", "q_term", "q_weight", "ranker", "t_submit", "t_done",
                 "t_queue_wait", "cache", "_event", "_result", "_error")

    def __init__(self, key: bytes, q_term: np.ndarray, q_weight: np.ndarray,
                 ranker: str = "tfidf"):
        self.key = key
        self.q_term = q_term
        self.q_weight = q_weight
        self.ranker = ranker
        self.t_submit = time.perf_counter()
        self.t_done: float | None = None
        self.t_queue_wait = 0.0
        self.cache = "miss"
        self._event = threading.Event()
        self._result: tuple[np.ndarray, np.ndarray] | None = None
        self._error: BaseException | None = None

    def _resolve(self, result: tuple[np.ndarray, np.ndarray]) -> None:
        self._result = result
        self.t_done = time.perf_counter()
        self._event.set()

    def _fail(self, exc: BaseException) -> None:
        self._error = exc
        self.t_done = time.perf_counter()
        self._event.set()

    @property
    def done(self) -> bool:
        """True once the request resolved or failed (non-blocking)."""
        return self._event.is_set()

    @property
    def error(self) -> BaseException | None:
        """The failure that resolved this request, or None (non-blocking;
        the soak's double-serve audit inspects abandoned futures)."""
        return self._error

    @property
    def latency_s(self) -> float | None:
        return None if self.t_done is None else self.t_done - self.t_submit

    def result(self, timeout: float | None = None) -> tuple[np.ndarray, np.ndarray]:
        """Block for this request's ``(scores[k], doc_ids[k])``; re-raises
        the batch's failure when its dispatch exhausted the ladder."""
        if not self._event.wait(timeout):
            raise TimeoutError("serve request did not complete in time")
        if self._error is not None:
            raise self._error
        assert self._result is not None
        return self._result


_STOP = object()


@dataclasses.dataclass
class _DevSegment:
    """Device-resident serving state of ONE live segment."""

    name: str
    doc_base: int
    n_docs: int
    nnz: int
    k: int  # per-segment top-k width (min(server k, n_docs))
    dev_doc: object  # int32 [nnz] on device
    dev_term: object  # int32 [nnz] on device (COO path; None on impacted)
    valid: object  # f[nnz] on device (COO path; None on impacted)
    weights: dict  # ranker -> device weight table [nnz]
    offsets: np.ndarray | None  # int64 [vocab+1] host CSC slice table
    # (None only on a legacy COO-only artifact)
    ranks: np.ndarray | None  # host prior source (segment-local slice)
    prior: object = None  # device every-request blend operand [n_docs]
    prior_req: object = None  # device ranker="prior" operand [n_docs]


@dataclasses.dataclass(frozen=True)
class _ServingView:
    """What ``server.index`` exposes when the server fronts a segment set
    (aggregate stats; the per-segment artifacts live in the set)."""

    version: int
    n_docs: int
    nnz: int
    vocab_bits: int
    cfg: TfidfConfig
    weight: np.ndarray  # zero-length dtype carrier
    ranks: np.ndarray | None
    bm25_weight: np.ndarray | None
    segments: int

    @property
    def vocab_size(self) -> int:
        return 1 << self.vocab_bits


def _set_view(segset: SegmentSet) -> _ServingView:
    dtype = segset.segments[0].weights["tfidf"].dtype
    marker = np.zeros(0, dtype)
    return _ServingView(
        version=segset.version,
        n_docs=segset.n_docs,
        nnz=segset.nnz,
        vocab_bits=segset.vocab_bits,
        cfg=segset.cfg,
        weight=marker,
        ranks=marker if segset.has_ranks else None,
        bm25_weight=marker if segset.has_bm25 else None,
        segments=len(segset.segments),
    )


def _check_impacted_servable(cfg: ServeConfig, segset: SegmentSet) -> None:
    """The impacted path needs real CSC offsets: a legacy (pre-offsets,
    non-term-sorted) artifact loads with ``term_offsets=None`` and can
    only serve via the COO path — refusing beats silently slicing runs
    that do not exist."""
    if cfg.scoring != "impacted":
        return
    for seg in segset.segments:
        if seg.term_offsets is None:
            raise ValueError(
                f"scoring='impacted' needs the CSC-by-term offsets, but "
                f"segment {seg.ref.name} is a legacy non-term-sorted "
                "artifact (COO-only) — rebuild it with this version, or "
                "serve with scoring='coo'"
            )
        if seg.ref.nnz >= 1 << 31:
            # bucket_start rides int32 on device; a single segment past
            # 2^31 postings would wrap its run starts into silently wrong
            # scores.  Split the corpus into segments (the layout this
            # PR exists for) instead of widening the device index path.
            raise ValueError(
                f"segment {seg.ref.name} holds {seg.ref.nnz} postings — "
                "impacted scoring addresses segments with int32 offsets; "
                "split the index into (merge-bounded) segments under "
                "2^31 nnz each"
            )


class TfidfServer:
    """The long-lived online query path over one :class:`ServableIndex`
    or a live :class:`~..serving.segments.SegmentSet`.

    Usage::

        index = serving.load_index("/path/to/index")
        with TfidfServer(index, ServeConfig(top_k=10)) as srv:
            scores, docs = srv.query(["apollo", "guidance"])

    ``start()`` device-puts the postings once and (by default) warms every
    padded batch shape, so steady state never compiles; ``submit`` is
    thread-safe and returns a future.  A segmented server additionally
    supports :meth:`refresh_segments` — hot-swapping the live set (a new
    delta commit, a background merge) WITHOUT restart.
    """

    def __init__(
        self,
        index: "ServableIndex | SegmentSet",
        cfg: ServeConfig = ServeConfig(),
        *,
        metrics: MetricsRecorder | None = None,
    ):
        if isinstance(index, SegmentSet):
            segset = index
            self.index: "ServableIndex | _ServingView" = _set_view(segset)
        else:
            segset = wrap_index_as_set(index)
            self.index = index
        if segset.n_docs < 1 or segset.nnz < 1:
            raise ValueError("cannot serve an empty index")
        if (cfg.rank_alpha > 0 or cfg.prior_alpha > 0) and not segset.has_ranks:
            raise ValueError(
                "rank_alpha/prior_alpha > 0 needs a PageRank prior in the "
                "index (save_index(..., ranks=...))"
            )
        _check_impacted_servable(cfg, segset)
        self._segset = segset
        self.cfg = cfg
        self.metrics = metrics or MetricsRecorder()
        self.k = min(cfg.top_k, segset.n_docs)
        self._queue: queue.Queue = queue.Queue(maxsize=cfg.queue_depth)
        self._thread: threading.Thread | None = None
        self._started = False
        self._stopped = False  # distinguishes drained from never-started
        self._cache: collections.OrderedDict[bytes, tuple] = collections.OrderedDict()
        self._lock = threading.Lock()  # cache + stats + live segment list
        # Orders submit()'s {started-check, enqueue} against stop()'s flag
        # flip.  Deliberately NOT self._lock: the drain thread takes that
        # one per batch, and a submitter may block on a full queue while
        # holding this lock — the drain must be free to keep consuming.
        self._submit_lock = threading.Lock()
        self._stats = collections.Counter()
        self._segs: list[_DevSegment] = []  # live device state, base order
        self._prior_gen = 0  # bumped per prior swap AND per segment
        # refresh; the stale-result cache guard
        self._use_prior = False
        self._bucket_cap = 0  # carried impacted bucket-count cap (pow2)
        # the last hot-swapped GLOBAL prior (set_prior): refresh_segments
        # re-applies it to the new live set — a commit landing between
        # two prior ticks must not silently revert serving to the
        # artifact-carried placeholder priors
        self._prior_ranks: np.ndarray | None = None

    # ------------------------------------------------------------ lifecycle

    def _build_seg(self, seg: LoadedSegment, k: int) -> _DevSegment:
        """Device-put one segment's serving state (mmap pages fault in
        exactly once; queries then touch only device memory)."""
        import jax.numpy as jnp

        idx = seg.index
        weights = {
            r: jnp.asarray(np.ascontiguousarray(w))
            for r, w in seg.weights.items()
        }
        coo = self.cfg.scoring == "coo"
        return _DevSegment(
            name=seg.ref.name,
            doc_base=seg.ref.doc_base,
            n_docs=idx.n_docs,
            nnz=idx.nnz,
            k=min(k, idx.n_docs),
            dev_doc=jnp.asarray(np.ascontiguousarray(idx.doc)),
            # the term array and validity mask are COO-path operands only
            # — the impacted scorer consumes doc + weights + host offsets,
            # so skipping these saves two nnz-sized device buffers per
            # segment (~140 MB at the 1M-doc bench scale, doubled
            # transiently during every refresh)
            dev_term=(jnp.asarray(np.ascontiguousarray(idx.term))
                      if coo else None),
            valid=(jnp.ones(idx.nnz, weights["tfidf"].dtype)
                   if coo else None),
            weights=weights,
            offsets=seg.term_offsets,
            ranks=(np.ascontiguousarray(idx.ranks)
                   if idx.ranks is not None else None),
        )

    def _build_segs(self, segset: SegmentSet, k: int) -> list[_DevSegment]:
        segs = [self._build_seg(s, k) for s in segset.segments]
        with self._lock:
            ranks = self._prior_ranks
        self._apply_prior(segs, ranks)
        return segs

    def start(self, warm: bool = True) -> "TfidfServer":
        """Load device state and launch the drain thread.  ``warm=True``
        compiles every padded batch shape before the first request."""
        if self._started:
            return self
        segset = self._segset
        with obs.span("serve.load", version=segset.version, nnz=segset.nnz,
                      segments=len(segset.segments)):
            self._use_prior = (
                self.cfg.rank_alpha > 0 or self.cfg.prior_alpha > 0
            )
            self._segs = self._build_segs(segset, self.k)
        self._started = True
        self._stopped = False
        if warm:
            self.warmup()
        self._thread = threading.Thread(
            target=self._drain, name="tfidf-serve-drain", daemon=True
        )
        self._thread.start()
        obs.emit("serve_start", version=segset.version, n_docs=segset.n_docs,
                 nnz=segset.nnz, k=self.k, max_batch=self.cfg.max_batch,
                 segments=len(segset.segments), scoring=self.cfg.scoring)
        return self

    def _warm_segs(self, segs: list[_DevSegment], k: int, *,
                   only: "set[str] | None" = None) -> list[int]:
        """Compile (and fence) every padded batch shape against ``segs``
        — shared by start-time warmup and segment refresh, so a request
        can only ever hit a warm executable.  One pass covers every
        ranker: the weight table is a traced operand of the same
        shape/dtype, so tfidf/bm25/prior share every executable.
        ``only`` restricts the per-segment dispatches to the named (NEW)
        segments — carried-over segments' executables are already
        compiled, and re-executing their warm passes on every refresh is
        pure CPU taken from live traffic; the cross-segment merge is
        always warmed (its shape depends on the whole set)."""
        caps = batch_shape_matrix(self.cfg.max_batch)
        q = self.cfg.max_query_terms
        if self.cfg.scoring == "impacted":
            # Pre-grow the carried bucket cap for a HEAVY plausible batch
            # — max_batch queries of a few terms each hitting the live
            # set's heaviest posting run — clipped by impact_warm_buckets.
            # A cap bump at serve time is an inline recompile on the
            # latency path; paying it here (bounded) is the
            # warm-shape-matrix discipline applied to the bucket axis.
            # Sized for typical traffic, not the adversarial worst
            # (max_query_terms stopwords): every dispatch gathers the
            # FULL padded cap, so an over-grown cap taxes each request —
            # a genuinely heavier stream grows past this with one logged
            # recompile per doubling.
            w = self.cfg.impact_bucket_width
            df_max = max(
                (int(np.diff(seg.offsets).max()) if seg.offsets.shape[0] > 1
                 else 0)
                for seg in segs
            )
            heavy_terms = min(q, 4)
            worst = (self.cfg.max_batch * heavy_terms
                     * ((df_max + w - 1) // w))
            target = min(max(worst, 1), self.cfg.impact_warm_buckets)
            with self._lock:
                cap_before = self._bucket_cap
                cap, _ = grow_chunk_cap(
                    target, self._bucket_cap, self.metrics,
                    min_bits=IMPACT_MIN_BUCKET_BITS,
                )
                self._bucket_cap = max(self._bucket_cap, cap)
                cap_grew = self._bucket_cap != cap_before
            if cap_grew:
                # the bucket axis changed shape for EVERY segment, not
                # just the new ones: carried-over executables compiled at
                # the old cap would recompile inline on the first live
                # request — warm the whole set this pass instead
                only = None
        dtype = segs[0].weights["tfidf"].dtype
        for cap in caps:
            with obs.span("serve.warmup", batch=cap,
                          scoring=self.cfg.scoring):
                zt = np.zeros((cap, q), np.int32)
                zw = np.zeros((cap, q), dtype)
                outs = []
                warm_set = [s for s in segs
                            if only is None or s.name in only]
                for seg in warm_set:
                    if self.cfg.scoring == "impacted":
                        zc = np.zeros(self._bucket_cap, np.int32)
                        outs.append(ops.score_impacted_batch(
                            seg.dev_doc, seg.weights["tfidf"],
                            zc, zc, zc, zc.astype(dtype), seg.prior,
                            n_docs=seg.n_docs, batch=cap,
                            bucket_width=self.cfg.impact_bucket_width,
                            k=seg.k, use_prior=self._use_prior,
                        ))
                    else:
                        outs.append(ops.score_query_batch(
                            seg.dev_doc, seg.dev_term,
                            seg.weights["tfidf"], seg.valid,
                            zt, zw, zw, seg.prior,
                            n_docs=seg.n_docs, vocab=self.vocab_size,
                            k=seg.k, use_prior=self._use_prior,
                        ))
                if len(segs) > 1:
                    # the merge program's shape depends on the WHOLE live
                    # set — warm it against zero candidates even when the
                    # per-segment dispatches were restricted to new ones
                    outs.append(ops.topk_merge(
                        tuple(np.zeros((cap, s.k), dtype) for s in segs),
                        tuple(np.zeros((cap, s.k), np.int32)
                              for s in segs),
                        tuple(s.doc_base for s in segs),
                        k=min(k, sum(s.k for s in segs)),
                    ))
                if outs:
                    rx.block_until_ready(
                        outs, site="serve_warmup", metrics=self.metrics
                    )
        return caps

    def warmup(self) -> list[int]:
        """Compile every padded batch shape the policy can produce for
        the CURRENT live segment set.  After this, a request can only
        ever hit a warm executable — the 'compiled runners warm' half of
        the serving tentpole."""
        with self._lock:
            segs = list(self._segs)
            k = self.k
        return self._warm_segs(segs, k)

    @property
    def vocab_size(self) -> int:
        return 1 << self._segset.vocab_bits

    def _apply_prior(self, segs: list[_DevSegment],
                     global_ranks: np.ndarray | None) -> None:
        """(Re)build each segment's two device prior operands — the
        every-request blend (``rank_alpha·ranks``) and the ranker="prior"
        blend (``(rank_alpha + prior_alpha)·ranks``) — from a GLOBAL
        ranks vector (sliced per segment by doc range) or, when None,
        from each segment's artifact-carried local prior.  Zeros when the
        server carries no prior.  The device operands are built OUTSIDE
        the lock (device_put is slow) and assigned to every segment in
        one locked section, so a batch snapshotting the live set never
        sees segment A under the new prior and segment B under the old."""
        import jax.numpy as jnp

        built = []
        for seg in segs:
            dtype = seg.weights["tfidf"].dtype
            if global_ranks is not None:
                local = np.ascontiguousarray(
                    global_ranks[seg.doc_base:seg.doc_base + seg.n_docs],
                    dtype)
                if local.shape[0] < seg.n_docs:
                    # a segment committed AFTER the last set_prior: its
                    # docs have no global rank yet — give them the
                    # neutral mean-1 value (priors are mean-normalized)
                    # until the next prior refresh covers them
                    pad = np.ones(seg.n_docs - local.shape[0], dtype)
                    local = np.concatenate([local, pad])
            elif seg.ranks is not None:
                local = np.ascontiguousarray(seg.ranks, dtype)
            else:
                local = None
            if local is None or not self._use_prior:
                base = np.zeros(seg.n_docs, dtype)
                req = base
            else:
                base = (self.cfg.rank_alpha * local
                        if self.cfg.rank_alpha > 0
                        else np.zeros(seg.n_docs, dtype))
                req = base + self.cfg.prior_alpha * local
            base_dev = jnp.asarray(base.astype(dtype))
            req_dev = (base_dev if req is base
                       else jnp.asarray(req.astype(dtype)))
            built.append((base_dev, req_dev))
        with self._lock:
            for seg, (base_dev, req_dev) in zip(segs, built):
                seg.prior = base_dev
                seg.prior_req = req_dev

    def set_prior(self, ranks: np.ndarray) -> None:
        """Hot-swap the PageRank prior on a RUNNING server (the soak's
        background refresh): rebuilds the per-segment prior operands from
        the GLOBAL ``ranks`` vector and invalidates the result cache
        (cached top-k blended the old prior).  No recompile — the prior
        is a traced operand of every warm executable.  Requires a server
        constructed with ``rank_alpha > 0`` or ``prior_alpha > 0``
        (otherwise the compiled program has no prior addend to feed)."""
        if not self._started:
            raise RuntimeError("server not started")
        if not self._use_prior:
            raise RuntimeError(
                "server compiled without a prior operand — construct with "
                "ServeConfig(rank_alpha=... ) or ServeConfig(prior_alpha=...)"
            )
        ranks = np.ascontiguousarray(ranks)
        with self._lock:
            segs = list(self._segs)
            n_docs = sum(s.n_docs for s in segs)
        if ranks.shape != (n_docs,):
            raise ValueError(
                f"prior has shape {ranks.shape}; this index holds "
                f"{n_docs} documents"
            )
        self._apply_prior(segs, ranks)
        with self._lock:
            self._prior_ranks = ranks  # re-applied by refresh_segments
            self._prior_gen += 1
            self._cache.clear()
        obs.emit("serve_prior_update", n_docs=int(ranks.shape[0]))

    def refresh_segments(self, segset: SegmentSet) -> None:
        """Hot-swap the live segment set WITHOUT restart (a new delta
        commit, a background merge): device state for the new set is
        built and warmed FIRST (compiles land here, off the serving
        path's critical decisions — in-flight batches keep scoring
        against the old, still-live buffers), then the list is swapped
        under the lock and the result cache invalidated by generation.
        Queued and future requests see the new set; nothing is dropped
        and nothing restarts."""
        if not self._started:
            raise RuntimeError("server not started")
        if segset.cfg.config_hash() != self._segset.cfg.config_hash():
            raise ValueError(
                "refusing to refresh across semantic config changes "
                f"({segset.cfg.config_hash()} != "
                f"{self._segset.cfg.config_hash()})"
            )
        _check_impacted_servable(self.cfg, segset)
        t0 = time.perf_counter()
        with obs.span("serve.refresh", version=segset.version,
                      segments=len(segset.segments)):
            new_k = min(self.cfg.top_k, segset.n_docs)
            segs = self._build_segs(segset, new_k)
            with self._lock:
                live = {s.name for s in self._segs}
            self._warm_segs(segs, new_k,
                            only={s.name for s in segs} - live)
            with self._lock:
                self._segset = segset
                self._segs = segs
                self.k = new_k
                self._prior_gen += 1
                self._cache.clear()
                self._stats["refreshes"] += 1
            # submit()'s ranker refusal checks read self.index — it must
            # describe the LIVE set, whatever the server was built from
            # (a plain-artifact server keeps its ServableIndex only until
            # the first refresh makes it stale)
            self.index = _set_view(segset)
        obs.emit("serve_refresh", version=segset.version,
                 segments=len(segset.segments), n_docs=segset.n_docs,
                 warm_s=round(time.perf_counter() - t0, 4))

    def stop(self) -> None:
        """Graceful shutdown: stop accepting, drain what's queued, fail
        anything that slipped past the drain with :class:`ServerShutdown`
        — clients always get an answer or a typed refusal, never a hang."""
        with self._submit_lock:
            self._started = False  # new submits refuse from here on
            self._stopped = True
        if self._thread is not None:
            self._queue.put(_STOP)
            self._thread.join()
            self._thread = None
        # A submit racing this shutdown can still have slipped a request in
        # around the sentinel; with the drain thread gone, fail it rather
        # than leave its future hanging forever.
        while True:
            try:
                item = self._queue.get_nowait()
            except queue.Empty:
                break
            if isinstance(item, _Pending):
                item._fail(ServerShutdown("server stopped"))
        obs.emit("serve_stop", **{k: int(v) for k, v in self._stats.items()})

    def __enter__(self) -> "TfidfServer":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.stop()

    # -------------------------------------------------------------- queries

    def make_query(self, terms: Sequence[str]) -> tuple[np.ndarray, np.ndarray]:
        """Host-side query prep: run the query through the INDEX's real
        tokenizer pipeline (``io.text.tokenize`` + ``add_ngrams`` with the
        artifact's config — so "state-of-the-art" splits exactly like the
        corpus did, and an ngram=2 index gets its bigram terms), then hash
        into canonical (term_ids, weights) — term-id-sorted, duplicates
        combined (weight = occurrence count, the A11 query vector),
        truncated to the ``max_query_terms`` hot slots."""
        cfg = self._segset.cfg
        dtype = self.index.weight.dtype
        toks: list[str] = []
        for t in terms:
            toks.extend(tio.tokenize(t, lowercase=cfg.lowercase,
                                     min_token_len=cfg.min_token_len))
        toks = tio.add_ngrams(toks, cfg.ngram)
        if not toks:
            return (np.zeros(0, np.int32), np.zeros(0, dtype))
        ids = tio.hash_to_vocab(tio.fnv1a_64(toks), cfg.vocab_bits)
        uniq, counts = np.unique(ids, return_counts=True)
        if uniq.shape[0] > self.cfg.max_query_terms:
            # keep the heaviest terms; stable enough for a hot path and
            # recorded so operators see truncation happening
            order = np.argsort(-counts, kind="stable")[: self.cfg.max_query_terms]
            order.sort()
            uniq, counts = uniq[order], counts[order]
            obs.counter("serve.query_truncated")
        return uniq.astype(np.int32), counts.astype(dtype)

    @staticmethod
    def query_key(q_term: np.ndarray, q_weight: np.ndarray,
                  ranker: str = "tfidf") -> bytes:
        """LRU key: hash of the canonical sparse query vector + the
        ranker that scored it (an A/B pair must never share a cache
        entry)."""
        h = hashlib.sha1()
        h.update(ranker.encode())
        h.update(q_term.tobytes())
        h.update(q_weight.tobytes())
        return h.digest()

    def submit(self, terms: Sequence[str], *, ranker: str = "tfidf") -> _Pending:
        """Enqueue one query; returns a future.  Blocks when the bounded
        queue is full (backpressure, not unbounded memory).  ``ranker``
        picks the weight table per request (the A/B switch): ``tfidf``
        always, ``bm25`` when the index artifact bundles BM25 weights."""
        if ranker not in RANKERS:
            raise ValueError(f"unknown ranker {ranker!r} (want {RANKERS})")
        if ranker == "bm25" and self.index.bm25_weight is None:
            raise ValueError(
                "this index carries no BM25 weights — rebuild with "
                "save_index(..., bm25=Bm25Config()) / cli.tfidf "
                "--save-index (BM25 is bundled by default)"
            )
        if ranker == "prior" and self.cfg.prior_alpha <= 0:
            raise ValueError(
                "ranker='prior' needs a per-request prior scale — construct "
                "the server with ServeConfig(prior_alpha=...) over an index "
                "saved with a ranks prior"
            )
        q_term, q_weight = self.make_query(terms)
        pending = _Pending(self.query_key(q_term, q_weight, ranker),
                           q_term, q_weight, ranker)
        with self._submit_lock:
            # the started-check AND the enqueue happen under the lock
            # stop() flips the flag under, so a racing submit either
            # raises here or its request is in the queue BEFORE the stop
            # sentinel (served, or failed by the leftover drain) — never
            # silently dropped with a hanging future
            if not self._started:
                if self._stopped:
                    raise ServerShutdown("server stopped")
                raise RuntimeError("server not started")
            self._queue.put(pending)  # graftlint: disable=blocking-under-lock (deliberate: backpressure belongs inside the started-check; the drain consumes without ever taking _submit_lock, so a blocked put always unblocks — see the _submit_lock comment above)
        with self._lock:
            self._stats["requests"] += 1
            # per-ranker traffic split for the A/B read-out — counted at
            # submit so cache hits are included, unlike the per-dispatch
            # tallies in _serve_group
            self._stats[f"requests_{ranker}"] += 1
        return pending

    def query(
        self, terms: Sequence[str], timeout: float | None = 30.0,
        *, ranker: str = "tfidf",
    ) -> tuple[np.ndarray, np.ndarray]:
        """Synchronous convenience wrapper: submit + wait."""
        return self.submit(terms, ranker=ranker).result(timeout)

    def stats(self) -> dict:
        with self._lock:
            out = {k: int(v) for k, v in self._stats.items()}
            out["segments"] = len(self._segs)
        out.setdefault("requests", 0)
        for key in ("cache_hits", "cache_misses", "dedup_hits", "batches",
                    "batch_errors", "refreshes", "peer_stores"):
            out.setdefault(key, 0)
        return out

    # ------------------------------------------------------- peer-cache hooks

    def cache_lookup(
        self, terms: Sequence[str], *, ranker: str = "tfidf",
    ) -> "tuple[np.ndarray, np.ndarray] | None":
        """Non-computing probe of the local result LRU under the SAME
        canonical key the serve path uses — the replica-side answer to a
        peer's ``POST /cache/peek`` (serving/fabric.py): a hit returns
        the cached ``(scores, docs)`` without touching the dispatch
        queue, a miss returns None and costs one tokenize."""
        q_term, q_weight = self.make_query(terms)
        return self._cache_get(self.query_key(q_term, q_weight, ranker))

    def cache_insert(
        self, terms: Sequence[str], scores, docs, *, ranker: str = "tfidf",
    ) -> bool:
        """Install an externally-computed result into the local LRU (the
        ``POST /cache/fill`` write-back from a non-owner replica).  The
        value is stored against the CURRENT prior-generation stamp, so a
        racing hot-swap invalidates it exactly like a locally-computed
        entry; values are stored in the serve path's native float32/int32
        — the wire carried doubles that ORIGINATED as float32 computes,
        so the f64→f32 cast is exact and a later hit re-serializes
        byte-identically to the compute that produced them."""
        q_term, q_weight = self.make_query(terms)
        key = self.query_key(q_term, q_weight, ranker)
        value = (np.asarray(scores, dtype=np.float32),
                 np.asarray(docs, dtype=np.int32))
        with self._lock:
            gen = self._prior_gen
        self._cache_put(key, value, gen)
        with self._lock:
            self._stats["peer_stores"] += 1
        return True

    # ---------------------------------------------------------- drain thread

    def _cache_get(self, key: bytes):
        if self.cfg.cache_size <= 0:
            return None
        with self._lock:
            hit = self._cache.get(key)
            if hit is not None:
                self._cache.move_to_end(key)
        return hit

    def _cache_put(self, key: bytes, value: tuple, gen: int) -> None:
        if self.cfg.cache_size <= 0:
            return
        with self._lock:
            if gen != self._prior_gen:
                # the batch was dispatched against a prior operand (or a
                # segment set) that set_prior/refresh_segments has since
                # hot-swapped: caching it would serve the stale result as
                # hits after the invalidation
                return
            self._cache[key] = value
            self._cache.move_to_end(key)
            while len(self._cache) > self.cfg.cache_size:
                self._cache.popitem(last=False)

    def _drain(self) -> None:
        """The micro-batching loop: block for one request, gather up to
        ``max_batch`` within ``flush_ms``, serve the batch, repeat."""
        while True:
            try:
                first = self._queue.get(timeout=0.2)
            except queue.Empty:
                continue
            if first is _STOP:
                return
            batch = [first]
            deadline = time.perf_counter() + self.cfg.flush_ms / 1e3
            stop_after = False
            while len(batch) < self.cfg.max_batch:
                wait = deadline - time.perf_counter()
                try:
                    item = (self._queue.get(timeout=wait) if wait > 0
                            else self._queue.get_nowait())
                except queue.Empty:
                    break
                if item is _STOP:
                    stop_after = True
                    break
                batch.append(item)
            try:
                self._serve_batch(batch)
            except Exception as exc:  # noqa: BLE001 — the drain must survive
                # _serve_batch guards the dispatch/pull internally; this
                # catches everything else (pad bookkeeping, a misbehaving
                # caller-supplied metrics recorder, cache publication) so
                # the ONLY queue consumer never dies: the batch's futures
                # fail, later requests keep serving.
                with self._lock:
                    self._stats["batch_errors"] += 1
                obs.counter("serve.batch_errors")
                for p in batch:
                    if not p._event.is_set():
                        p._fail(exc)
            if stop_after:
                return

    def _publish_request(self, p: _Pending, batch: int, error: str | None = None) -> None:
        obs.emit(
            "serve_request",
            cache=p.cache,
            queue_wait_s=round(p.t_queue_wait, 6),
            total_s=round(p.latency_s or 0.0, 6),
            batch=batch,
            **({"error": error} if error else {}),
        )
        obs.histogram("serve.latency_s", p.latency_s or 0.0)
        obs.histogram("serve.queue_wait_s", p.t_queue_wait)

    def _serve_batch(self, batch: list[_Pending]) -> None:
        t_dequeue = time.perf_counter()
        for p in batch:
            p.t_queue_wait = t_dequeue - p.t_submit
        with obs.span("serve.batch", size=len(batch)):
            misses: list[_Pending] = []
            for p in batch:
                hit = self._cache_get(p.key)
                if hit is not None:
                    p.cache = "hit"
                    p._resolve(hit)
                    with self._lock:
                        self._stats["cache_hits"] += 1
                    obs.counter("serve.cache_hits")
                    self._publish_request(p, batch=len(batch))
                else:
                    misses.append(p)
            if not misses:
                return
            # Per-ranker groups: an A/B batch dispatches once per ranker
            # present (the weight table is a per-dispatch operand; shapes
            # — and therefore executables — are shared, so a mixed batch
            # still never compiles).  The overwhelmingly common case is
            # one ranker per flush window = one dispatch, exactly the
            # pre-A/B behavior.
            by_ranker: dict[str, list[_Pending]] = {}
            for p in misses:
                by_ranker.setdefault(p.ranker, []).append(p)
            for ranker, plist in by_ranker.items():
                self._serve_group(ranker, plist, batch_size=len(batch))

    @staticmethod
    def _query_plan(uniq: list[_Pending], dtype):
        """Segment-INDEPENDENT half of the impacted planner: one flat
        (row, term id, query weight) triple per query term across the
        deduped batch — built once per batch, shared by every segment."""
        n_terms = [p.q_term.shape[0] for p in uniq]
        if sum(n_terms) == 0:
            return (np.zeros(0, np.int32), np.zeros(0, np.int64),
                    np.zeros(0, dtype))
        rows = np.repeat(np.arange(len(uniq), dtype=np.int32), n_terms)
        terms = np.concatenate([p.q_term for p in uniq]).astype(np.int64)
        qws = np.concatenate([p.q_weight for p in uniq]).astype(dtype)
        return rows, terms, qws

    def _plan_impacted(
        self, segs: list[_DevSegment], uniq: list[_Pending], dtype
    ) -> list[tuple[np.ndarray, np.ndarray, np.ndarray, np.ndarray, int]]:
        """Host half of the impacted-list path: slice every query term's
        posting run out of each segment's CSC offsets and pad the runs
        into fixed-width buckets (vectorized — no per-bucket Python).
        The query-side arrays are built once, the carried pow2 cap is
        grown ONCE for the batch's worst segment (one lock acquisition —
        monotonic under the lock, so a refresh warming on another thread
        can never race the cap back DOWN past an already-compiled size),
        and each segment gets (start, len, row, qw) arrays at that cap."""
        W = self.cfg.impact_bucket_width
        rows, terms, qws = self._query_plan(uniq, dtype)
        runs = []
        need = 1
        for seg in segs:
            off = seg.offsets
            starts = off[terms]
            lens = off[terms + 1] - starts
            nb = (lens + W - 1) // W  # buckets per run (0 = absent term)
            total = int(nb.sum())
            runs.append((starts, lens, nb, total))
            need = max(need, total)
        with self._lock:
            cap, _ = grow_chunk_cap(
                need, self._bucket_cap, self.metrics,
                min_bits=IMPACT_MIN_BUCKET_BITS)
            cap = self._bucket_cap = max(self._bucket_cap, cap)
        plans = []
        for starts, lens, nb, total in runs:
            cum = np.cumsum(nb) - nb
            intra = np.arange(total, dtype=np.int64) - np.repeat(cum, nb)
            b_start = np.zeros(cap, np.int32)
            b_len = np.zeros(cap, np.int32)
            b_row = np.zeros(cap, np.int32)
            b_qw = np.zeros(cap, dtype)
            b_start[:total] = (np.repeat(starts, nb)
                               + W * intra).astype(np.int32)
            b_len[:total] = np.minimum(
                W, np.repeat(lens, nb) - W * intra).astype(np.int32)
            b_row[:total] = np.repeat(rows, nb)
            b_qw[:total] = np.repeat(qws, nb)
            plans.append((b_start, b_len, b_row, b_qw, total))
        return plans

    def _serve_group(self, ranker: str, misses: list[_Pending],
                     *, batch_size: int) -> None:
        """Dedup, pad, dispatch and resolve one ranker's share of a
        micro-batch — across every live segment, merged on device."""
        # In-batch dedup: N copies of one hot query arriving inside a
        # single flush window dispatch ONCE (the cache can only serve
        # repeats across batches; this closes the within-batch gap).
        groups: dict[bytes, list[_Pending]] = {}
        for p in misses:
            groups.setdefault(p.key, []).append(p)
        uniq = [ps[0] for ps in groups.values()]
        for ps in groups.values():
            for p in ps[1:]:
                p.cache = "dedup"
        with self._lock:
            self._stats["cache_misses"] += len(uniq)
            self._stats["dedup_hits"] += len(misses) - len(uniq)
            self._stats["batches"] += 1
            # the live set + per-segment prior operands + generation,
            # read atomically: a refresh or set_prior landing mid-batch
            # cannot smuggle this batch's result past its cache
            # invalidation, and every segment of this batch scores under
            # ONE prior generation (old buffers stay live for the
            # in-flight dispatch — jax arrays are refcounted)
            segs = list(self._segs)
            priors = [s.prior_req if ranker == "prior" else s.prior
                      for s in segs]
            prior_gen = self._prior_gen
            k = self.k
        obs.counter("serve.cache_misses", len(uniq))

        q = self.cfg.max_query_terms
        cap = batch_cap(len(uniq), self.cfg.max_batch, self.metrics)
        impacted = self.cfg.scoring == "impacted"
        dtype = segs[0].weights["tfidf"].dtype
        with obs.span("serve.pad", size=len(uniq), cap=cap, ranker=ranker,
                      segments=len(segs)):
            if impacted:
                plans = self._plan_impacted(segs, uniq, dtype)
            else:
                q_term = np.zeros((cap, q), np.int32)
                q_weight = np.zeros((cap, q), dtype)
                q_valid = np.zeros((cap, q), dtype)
                for i, p in enumerate(uniq):
                    m = min(p.q_term.shape[0], q)
                    q_term[i, :m] = p.q_term[:m]
                    q_weight[i, :m] = p.q_weight[:m]
                    q_valid[i, :m] = 1.0

        # ranker="prior" is the tfidf table with the per-request prior
        # operand; tfidf/bm25 ride the every-request (rank_alpha) operand.
        def dispatch():
            outs = []
            for seg, prior, extra in zip(
                    segs, priors, plans if impacted else segs):
                table = seg.weights["tfidf" if ranker == "prior" else ranker]
                if impacted:
                    b_start, b_len, b_row, b_qw, _total = extra
                    outs.append(ops.score_impacted_batch(
                        seg.dev_doc, table, b_start, b_len, b_row, b_qw,
                        prior, n_docs=seg.n_docs, batch=cap,
                        bucket_width=self.cfg.impact_bucket_width,
                        k=seg.k, use_prior=self._use_prior,
                    ))
                else:
                    outs.append(ops.score_query_batch(
                        seg.dev_doc, seg.dev_term, table, seg.valid,
                        q_term, q_weight, q_valid, prior,
                        n_docs=seg.n_docs, vocab=self.vocab_size,
                        k=seg.k, use_prior=self._use_prior,
                    ))
            if len(outs) == 1:
                # single live segment: doc ids are already global (base
                # 0) — byte-identical to the pre-segment serving path
                return outs[0]
            return ops.topk_merge(
                tuple(o[0] for o in outs),
                tuple(o[1] for o in outs),
                tuple(s.doc_base for s in segs),
                k=min(k, sum(s.k for s in segs)),
            )

        try:
            with obs.span("serve.dispatch", cap=cap, ranker=ranker,
                          segments=len(segs), scoring=self.cfg.scoring):
                scores_dev, idx_dev = rx.run_guarded(
                    dispatch, site="serve_dispatch", metrics=self.metrics,
                )
            with obs.span("serve.pull", cap=cap):
                # ONE batched [cap, k] pull — the only bytes that ever
                # cross device->host per batch
                scores, idx = rx.device_get(
                    (scores_dev, idx_dev), site="serve_pull",
                    metrics=self.metrics,
                )
        except Exception as exc:  # noqa: BLE001 — isolated per batch
            # fail exactly this group's requests; the drain loop (and
            # every other queued request) keeps going — per-request
            # degradation, not a server crash
            with self._lock:
                self._stats["batch_errors"] += 1
            obs.counter("serve.batch_errors")
            err = f"{type(exc).__name__}: {exc}"[:200]
            for p in misses:
                p._fail(exc)
                self._publish_request(p, batch=batch_size, error=err)
            return
        for i, key in enumerate(groups):
            result = (scores[i].copy(), idx[i].copy())
            self._cache_put(key, result, prior_gen)
            for p in groups[key]:
                p._resolve(result)
                self._publish_request(p, batch=batch_size)
