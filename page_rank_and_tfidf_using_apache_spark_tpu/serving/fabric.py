"""Multi-process serving fabric (ISSUE 17): a replica fleet behind a
consistent-hash router, scaled out past one process.

Everything before this module survives faults inside ONE process; Spark's
actual resilience story is a driver coordinating executor *processes*
that die and get replaced (PAPER.md's driver/executor correspondence).
Here the immutable segment artifacts + atomic LATEST pointer already make
cross-process index sharing free — N replica processes mmap the SAME
segment files — so this module adds only the coordination:

- **Replica** (``python -m ...serving.fabric --replica INDEX_DIR``): one
  :class:`~.server.TfidfServer` process that mmap-loads the live segment
  set, serves ``POST /query`` over the obs/export HTTP endpoint (same
  server, same ``/healthz`` the router health-checks), polls the manifest
  and hot-swaps independently, and keeps an idempotent request-id cache
  so a re-dispatched query is *replayed*, never re-executed.
- **Generation floor** (:func:`commit_floor` / :func:`read_floor`): the
  fleet's committed generation, durably written next to the manifest.
  ENFORCED, not advisory: a replica whose loaded generation is below the
  floor reports ``/healthz`` 503 and refuses queries — a replica
  restarted mid-rolling-swap cannot quietly serve a pre-floor artifact
  (the tier-5 kill-point harness covers the floor-commit write boundary).
- **Router** (:class:`ServingFabric`): consistent-hash query routing
  (``ring_slots`` vnodes per replica, so the per-replica LRU becomes a
  sharded distributed cache and at most ~1/N of keys remap when a
  replica leaves), health checking, and sibling retry of a failed
  replica's in-flight queries under the SAME request id — the soak's
  dropped=0 / double_served=0 audit extends across processes.
- **Supervisor**: respawns dead replicas through the declared ``respawn``
  ladder rung (:mod:`resilience.process`) and drives rolling restarts:
  wait for the fleet to reach generation G, commit the floor at G, then
  TERM→respawn one replica at a time while siblings keep serving.

ISSUE 19 grows the fleet a shared observability plane and closes the
ROADMAP's autoscaling follow-on on it:

- **Fleet federation**: the router owns a :class:`obs.federation.FleetHub`
  that scrapes every replica's ``/snapshot.json`` (guarded ``fed_scrape``
  site, staleness-labeled, never routing-blocking) and serves the exact
  fleet merge from the ROUTER's own ``/snapshot.json`` + ``/metrics``.
- **Membership is dynamic**: replicas live in id-keyed maps and the hash
  ring is rebuilt on membership change — :meth:`ServingFabric.scale_up`
  spawns a NEW id (survivor-owned keys never remap), and
  :meth:`ServingFabric.scale_down` drains the newest id (out of the ring
  first, then SIGTERM; in-flight queries finish or re-dispatch typed).
- **Autoscaler**: a control loop that reads ONLY the fleet hub —
  availability/latency burn rate and queue-wait p99 scale up, sustained
  idle scales down — bounded by min/max, rate-limited by a cooldown, and
  hysteretic (the scale-down thresholds sit far below the scale-up ones,
  so one noisy window cannot flap the fleet).  Every decision is
  published as an ``autoscale`` event carrying its measured inputs;
  ``tools/trace_report.py`` renders the timeline and ``tools/trace_diff.py``
  gates on flap count.

ISSUE 20 adds two cooperating robustness layers:

- **Drain by handoff, not retry**: with ``FabricConfig.handoff`` (default
  on where the platform has ``SO_REUSEPORT``) every replica id owns a
  FIXED port reserved by the router, and replica listeners join an
  ``SO_REUSEPORT`` group on it.  :meth:`ServingFabric.rolling_restart`
  spawns the successor FIRST (``--ready-at-floor``: its handshake only
  prints once it serves >= the committed floor on the shared port), then
  SIGTERMs the predecessor, which stops accepting, drains its in-flight
  requests to completion (non-daemon handler threads joined on close)
  and exits — the kernel steers new connections to the successor the
  whole time, so a roll under load needs ZERO sibling retries (the
  ``roll_retries`` audit key pins this).  The suspect/retry machinery
  stays as the UNPLANNED-failure path.
- **Sharded distributed result cache**: the consistent-hash ring owner
  of an affinity key is its cache authority.  A non-owner replica that
  misses its local LRU issues a bounded-deadline ``POST /cache/peek`` to
  the owner before computing, and fills the owner back with an
  idempotent-by-rid ``POST /cache/fill`` after computing.  Every peer
  interaction sits behind a per-peer circuit breaker (trip on
  consecutive timeouts, half-open probe; ``GRAFT_CACHE_*`` knobs) and
  falls back to local compute, so a slow/partitioned/dead peer can never
  add more than the peek deadline to p99 — graceful degradation to
  exactly the PR-17 local-LRU behavior.  The router broadcasts the
  id→port map over ``POST /peers`` on every membership change.

Process-level chaos rides the deterministic ``GRAFT_CHAOS`` grammar:
``replica_query:proc_kill@N`` SIGKILLs a replica mid-query (injected in
THAT replica's environment via ``FabricConfig.replica_chaos``),
``replica_swap:proc_kill@1`` kills it mid-hot-swap, and
``fabric_route:net_partition@N`` / ``fabric_route:net_hang@N:ms`` fault
the router→replica hop.  ISSUE 20 adds ``drain_handoff`` (the successor
spawn of a handoff roll), ``cache_peek`` and ``cache_fill`` (the peer
cache hops — ``net_partition``/``net_hang`` model a partitioned or slow
peer).  All sites are guarded through
``resilience.executor.attempt_once`` — one chaos-hooked attempt each;
the recovery loop (sibling retry, supervisor respawn, breaker + local
fallback) lives HERE, which is exactly what attempt_once is for.
"""

from __future__ import annotations

import argparse
import collections
import dataclasses
import hashlib
import itertools
import json
import os
import queue
import signal
import socket
import sys
import tempfile
import threading
import time
import urllib.error
import urllib.request
from typing import Any, Sequence

import numpy as np

from page_rank_and_tfidf_using_apache_spark_tpu import obs
from page_rank_and_tfidf_using_apache_spark_tpu.obs.federation import FleetHub
from page_rank_and_tfidf_using_apache_spark_tpu.obs.metrics import (
    MetricsHub,
    TelemetrySink,
)
from page_rank_and_tfidf_using_apache_spark_tpu.resilience import chaos
from page_rank_and_tfidf_using_apache_spark_tpu.resilience import (
    executor as rx,
)
from page_rank_and_tfidf_using_apache_spark_tpu.resilience import (
    process as procs,
)
from page_rank_and_tfidf_using_apache_spark_tpu.utils import checkpoint as ckpt
from page_rank_and_tfidf_using_apache_spark_tpu.utils.metrics import percentile

# Guarded chaos/retry sites of the fabric (tools/chaos.sh + tests name
# them; tier-4 chaos-coverage-drift audits that every site stays covered):
# the router→replica hop, the replica's hot-swap, the replica's query
# execution, the handoff successor spawn, and the two peer-cache hops.
ROUTE_SITE = "fabric_route"
SWAP_SITE = "replica_swap"
QUERY_SITE = "replica_query"
DRAIN_SITE = "drain_handoff"
PEEK_SITE = "cache_peek"
FILL_SITE = "cache_fill"

# The fleet's committed generation, next to LATEST in the index dir.
FLOOR_FILE = "FABRIC_FLOOR"


def _peer_knobs() -> "tuple[float, int, float]":
    """The declared peer-cache knobs (utils/config.py GRAFT_ENV_KNOBS +
    README env-knob table): peek deadline, breaker trip count, breaker
    half-open probe period."""
    deadline = float(os.environ.get("GRAFT_CACHE_PEEK_DEADLINE_S") or 0.25)
    trip = int(os.environ.get("GRAFT_CACHE_BREAKER_TRIP") or 3)
    probe = float(os.environ.get("GRAFT_CACHE_BREAKER_PROBE_S") or 2.0)
    return deadline, trip, probe


class FabricExhausted(RuntimeError):
    """A query ran out of sibling retries — every replica was dead,
    partitioned, or below the generation floor for the whole retry
    window.  The router-side analog of ResilienceExhausted."""


# --------------------------------------------------------------- floor


def commit_floor(index_dir: str, generation: int) -> None:
    """Durably commit the fleet's generation floor: no replica may serve
    a generation below this after the write lands.  Same atomic-write
    discipline as every other artifact (stage in a same-dir tmp, fsync,
    rename) — a SIGKILL at any boundary leaves the old floor or the new
    floor, never a torn file (the tier-5 'floor' kill-point scenario
    sweeps exactly this function)."""
    doc = {"floor": int(generation), "committed_wall": time.time()}
    path = os.path.join(index_dir, FLOOR_FILE)
    fd, tmp = tempfile.mkstemp(dir=index_dir, suffix=".floor.tmp")
    try:
        with os.fdopen(fd, "w") as f:
            json.dump(doc, f)
            f.write("\n")
        ckpt.durable_replace(tmp, path)
    finally:
        if os.path.exists(tmp):
            os.unlink(tmp)
    obs.emit("fabric_floor", floor=int(generation))


def read_floor(index_dir: str) -> int:
    """The committed generation floor; 0 when none was ever committed
    (every generation is servable)."""
    try:
        with open(os.path.join(index_dir, FLOOR_FILE)) as f:
            doc = json.load(f)
    except (OSError, json.JSONDecodeError):
        return 0
    return int(doc.get("floor", 0))


# --------------------------------------------------------------- config


@dataclasses.dataclass(frozen=True)
class FabricConfig:
    """Fleet shape + routing/retry/respawn policy."""

    replicas: int = 2
    ring_slots: int = 64  # vnodes per replica on the hash ring
    top_k: int = 10
    max_batch: int | None = None  # None: replica resolves its own ladder
    scoring: str = "coo"
    poll_s: float = 0.3  # replica manifest/floor poll period
    health_period_s: float = 0.5  # router health-check + stats-fold period
    request_timeout_s: float = 10.0  # one router→replica HTTP attempt
    retry_limit: int = 40  # sibling re-dispatch attempts per query
    retry_pause_s: float = 0.25  # pause between re-dispatches (lets the
    # supervisor respawn a dead replica inside the retry window)
    ready_timeout_s: float = 120.0  # replica spawn→handshake deadline
    grace_s: float = 15.0  # rolling restart: SIGTERM→SIGKILL deadline
    respawn: bool = True  # supervisor replaces dead replicas
    replica_chaos: tuple = ()  # ((replica_idx, GRAFT_CHAOS spec), ...):
    # targeted replica-side injection — the spec lands in THAT replica's
    # environment only, so a proc_kill schedule is per-process-deterministic
    federation: bool = True  # router-side FleetHub + fleet exporter
    fleet_window_s: float = 60.0  # fleet hub window (MUST match the
    # replicas' default hub window — merge raises on mismatch)
    latency_slo_s: float | None = None  # fleet latency budget (None: off)
    availability_target: float | None = None  # fleet availability budget
    handoff: bool = True  # rolling restarts drain by SO_REUSEPORT socket
    # handoff (successor first on the SAME port, predecessor drains) —
    # zero roll-attributed retries; auto-off where the platform lacks
    # SO_REUSEPORT, falling back to the PR-17 retry-carried roll
    peer_cache: bool = True  # owner-routed sharded result cache: the
    # router pushes the id→port map (POST /peers) so replicas peek the
    # ring owner before computing and fill it back after; off = the
    # PR-17 local-only LRUs (the bench A/B arm)
    cache_size: int | None = None  # per-replica result-LRU size override
    # (None: the replica's ServeConfig default; the bench's skewed A/B
    # shrinks it to make fleet-wide duplicate computes measurable)

    @staticmethod
    def from_env(**overrides) -> "FabricConfig":
        if "replicas" not in overrides:
            raw = os.environ.get("GRAFT_FABRIC_REPLICAS")
            if raw:
                overrides["replicas"] = int(raw)
        return FabricConfig(**overrides)


@dataclasses.dataclass(frozen=True)
class AutoscaleConfig:
    """Autoscaler policy: bounds, cadence, and the up/down thresholds.

    Hysteresis is structural: scaling UP needs acute pressure (budget
    burn >= ``burn_up`` — budget consumed at twice the sustainable rate —
    or queue-wait p99 over ``queue_p99_up_s``), while scaling DOWN needs
    the opposite extreme *sustained* (offered rate under
    ``idle_rate_down`` AND burn under ``burn_down`` for ``idle_hold_s``
    straight).  The dead band between them plus the cooldown is what the
    flap-count gate in tools/trace_diff.py relies on."""

    min_replicas: int = 1
    max_replicas: int = 4
    cooldown_s: float = 10.0  # min seconds between scale actions
    period_s: float = 1.0  # control-loop evaluation cadence
    burn_up: float = 2.0  # any budget burning >= 2x its rate: scale up
    queue_p99_up_s: float = 0.5  # queue-wait p99 over this: scale up
    burn_down: float = 0.5  # burn must be under this to call the fleet idle
    idle_rate_down: float = 0.5  # req/s under this counts as idle
    idle_hold_s: float = 5.0  # idle must hold this long before scale-down

    @staticmethod
    def from_env(**overrides) -> "AutoscaleConfig":
        env = {
            "min_replicas": os.environ.get("GRAFT_AUTOSCALE_MIN"),
            "max_replicas": os.environ.get("GRAFT_AUTOSCALE_MAX"),
            "cooldown_s": os.environ.get("GRAFT_AUTOSCALE_COOLDOWN_S"),
        }
        for key, raw in env.items():
            if raw and key not in overrides:
                overrides[key] = float(raw) if key.endswith("_s") else int(raw)
        return AutoscaleConfig(**overrides)


# --------------------------------------------------------------- ring


def _h(key: str) -> int:
    return int.from_bytes(hashlib.sha1(key.encode()).digest()[:8], "big")


class _Ring:
    """Consistent-hash ring: ``slots`` vnodes per replica.  A replica
    leaving removes only ITS vnodes — keys owned by survivors keep their
    owner (the ≤1/N remap property the stability test pins)."""

    def __init__(self, replica_ids: Sequence[int], slots: int):
        points: list[tuple[int, int]] = []
        for rid in replica_ids:
            for s in range(slots):
                points.append((_h(f"replica-{rid}#{s}"), rid))
        points.sort()
        self._points = points

    def route(self, key: str, *, exclude: "set[int] | None" = None) -> list[int]:
        """Replica preference order for ``key``: clockwise walk from the
        key's ring position, first occurrence of each replica; excluded
        (suspect) replicas move to the back rather than vanishing — with
        every replica suspect the caller still gets a candidate."""
        if not self._points:
            return []
        hv = _h(key)
        lo, hi = 0, len(self._points)
        while lo < hi:
            mid = (lo + hi) // 2
            if self._points[mid][0] < hv:
                lo = mid + 1
            else:
                hi = mid
        order: list[int] = []
        for i in range(len(self._points)):
            rid = self._points[(lo + i) % len(self._points)][1]
            if rid not in order:
                order.append(rid)
        if exclude:
            order = ([r for r in order if r not in exclude]
                     + [r for r in order if r in exclude])
        return order


def affinity_key(terms: Sequence[str], ranker: str) -> str:
    """The routing key: canonicalized like the server's result-cache key
    (ranker + sorted unique terms), so the SAME logical query always
    lands on the SAME replica and the per-replica LRU shards cleanly."""
    return ranker + "|" + " ".join(sorted(set(terms)))


# --------------------------------------------------------------- breaker


class _Breaker:
    """Per-peer circuit breaker for the cache peek/fill hops (state is
    guarded by the owning replica's ``_peer_lock``; this class holds no
    lock of its own).

    closed → (``trip`` consecutive failures) → open → (``probe_s``
    elapsed) → half_open: exactly ONE probe flies, success closes,
    failure re-opens and re-arms the probe timer.  While open (or while
    the half-open probe is outstanding) ``allow`` answers False and the
    caller computes locally — a dead peer costs nothing per request."""

    def __init__(self, trip: int, probe_s: float):
        self.trip = max(1, int(trip))
        self.probe_s = float(probe_s)
        self.failures = 0
        self.state = "closed"
        self.opened_t = 0.0

    def allow(self, now: float) -> bool:
        if self.state == "closed":
            return True
        if self.state == "open" and now - self.opened_t >= self.probe_s:
            self.state = "half_open"  # this caller IS the probe
            return True
        return False

    def record_success(self) -> None:
        self.failures = 0
        self.state = "closed"

    def record_failure(self, now: float) -> None:
        self.failures += 1
        if self.state == "half_open" or self.failures >= self.trip:
            self.state = "open"
            self.opened_t = now


# --------------------------------------------------------------- replica


def _percentiles_ms(lat: "collections.deque[float]") -> tuple[Any, Any]:
    if not lat:
        return None, None
    xs = sorted(lat)
    return (round(percentile(xs, 0.50) * 1e3, 3),
            round(percentile(xs, 0.99) * 1e3, 3))


class _Replica:
    """The replica-process runtime: one TfidfServer + the floor-enforcing
    poll loop + the idempotent query surface."""

    def __init__(self, index_dir: str, *, replica_id: int, top_k: int,
                 max_batch: int | None, scoring: str, poll_s: float,
                 rid_cache: int = 4096, cache_size: "int | None" = None):
        self.index_dir = index_dir
        self.replica_id = replica_id
        self.top_k = top_k
        self.max_batch = max_batch
        self.scoring = scoring
        self.poll_s = poll_s
        self.cache_size = cache_size
        self.srv = None  # TfidfServer once a servable generation loaded
        self.generation: int | None = None
        self.floor = read_floor(index_dir)
        # rid → cached response body: a re-dispatched request id replays
        # the SAME bytes instead of re-executing (the cross-process
        # double-serve guard); capped LRU
        self._rid_cache: collections.OrderedDict[str, tuple] = (
            collections.OrderedDict()
        )
        self._rid_cap = rid_cache
        self._lock = threading.Lock()  # floor/generation/rid-cache/latencies
        self._lat: collections.deque = collections.deque(maxlen=512)
        self._executions = 0
        self._replays = 0
        # Sharded-cache peer state (ISSUE 20), all under its OWN lock so
        # peer bookkeeping never contends with the serving hot path:
        # id→port map + authority ring pushed by the router (POST
        # /peers), one circuit breaker per peer, and the peer tallies.
        self._peer_lock = threading.Lock()
        self._peers: dict[int, int] = {}
        self._peer_ring: "_Ring | None" = None
        self._breakers: dict[int, _Breaker] = {}
        self._peer_stats: collections.Counter = collections.Counter()
        (self._peek_deadline_s, self._breaker_trip,
         self._breaker_probe_s) = _peer_knobs()
        # write-backs to the owner are asynchronous and best-effort: a
        # bounded queue drained by fabric-peer-fill; full = drop (the
        # owner just stays cold for that key)
        self._fill_q: "queue.Queue" = queue.Queue(maxsize=256)
        self._fill_thread: threading.Thread | None = None
        self._stop = threading.Event()
        self._poll_thread: threading.Thread | None = None

    # ----------------------------------------------------------- lifecycle

    def start(self) -> "_Replica":
        self._try_load()  # may come up unready (below floor / no manifest)
        self._poll_thread = threading.Thread(
            target=self._poll_loop, name="fabric-replica-poll", daemon=True
        )
        self._poll_thread.start()
        self._fill_thread = threading.Thread(
            target=self._fill_loop, name="fabric-peer-fill", daemon=True
        )
        self._fill_thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        try:
            self._fill_q.put_nowait(None)  # fill-loop shutdown sentinel
        except queue.Full:
            pass  # daemon thread; pending fills are best-effort anyway
        if self._poll_thread is not None:
            self._poll_thread.join(timeout=10.0)
            self._poll_thread = None
        if self._fill_thread is not None:
            self._fill_thread.join(timeout=5.0)
            self._fill_thread = None
        if self.srv is not None:
            self.srv.stop()

    def ready(self) -> bool:
        with self._lock:
            return (self.srv is not None and self.generation is not None
                    and self.generation >= self.floor)

    # ----------------------------------------------------------- load/swap

    def _serve_config(self):
        from page_rank_and_tfidf_using_apache_spark_tpu.serving.server import (
            ServeConfig,
        )
        from page_rank_and_tfidf_using_apache_spark_tpu.utils.config import (
            load_tuned_profile,
            tuned_config,
        )

        kwargs: dict = dict(top_k=self.top_k, max_batch=self.max_batch,
                            scoring=self.scoring)
        if self.cache_size is not None:
            kwargs["cache_size"] = self.cache_size
        return tuned_config(ServeConfig, load_tuned_profile(), **kwargs)

    def _try_load(self) -> None:
        """Initial load — refused outright while the newest committed
        manifest is below the floor: a replica restarted mid-rolling-swap
        must NOT serve the pre-floor artifact it can still see on disk;
        it stays unready and keeps polling until the fleet's generation
        catches up."""
        from page_rank_and_tfidf_using_apache_spark_tpu.serving import (
            segments as sgm,
        )
        from page_rank_and_tfidf_using_apache_spark_tpu.serving.server import (
            TfidfServer,
        )

        version = sgm.manifest_version(self.index_dir)
        with self._lock:
            floor = self.floor
        if version is None or version < floor:
            obs.emit("fabric_refuse", replica=self.replica_id,
                     version=version, floor=floor)
            return
        segset = sgm.load_segment_set(self.index_dir, mmap=True)
        srv = TfidfServer(segset, self._serve_config()).start()
        with self._lock:
            self.srv = srv
            self.generation = segset.version

    def _poll_loop(self) -> None:
        from page_rank_and_tfidf_using_apache_spark_tpu.serving import (
            segments as sgm,
        )

        while not self._stop.wait(self.poll_s):
            floor = read_floor(self.index_dir)
            with self._lock:
                if floor > self.floor:
                    self.floor = floor
                gen = self.generation
            if self.srv is None:
                try:
                    self._try_load()
                except Exception as exc:  # noqa: BLE001 — keep polling
                    obs.emit("fabric_load_error", replica=self.replica_id,
                             error=f"{type(exc).__name__}: {exc}"[:200])
                continue
            version = sgm.manifest_version(self.index_dir)
            if version is None or gen is None or version <= gen:
                continue
            try:
                # ONE chaos-hooked swap attempt (proc_kill here is the
                # kill-during-hot-swap scenario); a failed swap keeps the
                # old generation live and the next tick retries
                segset = rx.attempt_once(
                    lambda: sgm.load_segment_set(self.index_dir, mmap=True),
                    site=SWAP_SITE,
                )
                self.srv.refresh_segments(segset)
                with self._lock:
                    self.generation = segset.version
                obs.emit("fabric_swap", replica=self.replica_id,
                         generation=segset.version, floor=floor)
            except Exception as exc:  # noqa: BLE001 — swap again next tick
                obs.emit("fabric_swap_error", replica=self.replica_id,
                         error=f"{type(exc).__name__}: {exc}"[:200])

    # ------------------------------------------------------ sharded cache

    def _cache_owner(self, terms, ranker: str) -> "int | None":
        """The ring authority for this query's affinity key, or None when
        no peer topology has been pushed (single replica / peer cache
        off) — the caller then behaves exactly like PR-17 local-only."""
        key = affinity_key(terms, ranker)
        with self._peer_lock:
            ring = self._peer_ring
            if ring is None:
                return None
            route = ring.route(key)
        return route[0] if route else None

    def _peer_post(self, port: int, path: str, doc: dict,
                   timeout: float) -> dict:
        """Blocking JSON POST to a sibling replica on localhost.  Lives
        outside the reader methods so their wire contract stays exactly
        one request-shaped dict literal each (tier 6)."""
        data = json.dumps(doc).encode("utf-8")
        req = urllib.request.Request(
            f"http://127.0.0.1:{port}{path}", data=data, method="POST")
        req.add_header("Content-Type", "application/json")
        with urllib.request.urlopen(req, timeout=timeout) as fh:
            return json.loads(fh.read().decode("utf-8"))

    def _breaker_for(self, owner: int) -> "_Breaker | None":
        with self._peer_lock:
            br = self._breakers.get(owner)
            if br is None:
                return None
            before = br.state
            allowed = br.allow(time.monotonic())
            if br.state != before:
                self._emit_breaker(owner, before, br.state)
            return br if allowed else None

    def _emit_breaker(self, owner: int, old: str, new: str) -> None:
        """Caller holds ``_peer_lock``."""
        self._peer_stats["breaker_transitions"] += 1
        if new == "open":
            self._peer_stats["breaker_trips"] += 1
        obs.counter("cache_breaker_transitions")
        obs.emit("cache_breaker", replica=self.replica_id, peer=owner,
                 old=old, new=new)

    def _record_peer_outcome(self, owner: int, *, ok: bool) -> None:
        with self._peer_lock:
            br = self._breakers.get(owner)
            if br is None:
                return
            before = br.state
            if ok:
                br.record_success()
            else:
                br.record_failure(time.monotonic())
            if br.state != before:
                self._emit_breaker(owner, before, br.state)

    def _peek_owner(self, owner: int, terms, ranker: str):
        """Bounded-deadline cache peek at the ring authority.

        The HTTP round-trip runs on a disposable worker thread joined for
        at most the peek deadline: a hung/partitioned peer (chaos
        ``net_hang``) costs this request exactly the deadline, never
        more, and the abandoned daemon worker is reaped when its socket
        timeout fires.  Any failure → breaker bookkeeping + None (caller
        computes locally — graceful degradation to PR-17 behavior)."""
        if self._breaker_for(owner) is None:
            with self._peer_lock:
                self._peer_stats["peeks_skipped_open"] += 1
            return None
        with self._peer_lock:
            port = self._peers.get(owner)
        if port is None:
            return None
        doc = {"terms": list(terms), "ranker": ranker}
        cell: list = []

        def _worker() -> None:
            try:
                rx.attempt_once(
                    lambda: cell.append(
                        self._peer_post(port, "/cache/peek", doc,
                                        self._peek_deadline_s)),
                    site=PEEK_SITE,
                )
            except Exception as exc:  # noqa: BLE001 — captured for outcome
                cell.append(exc)

        t0 = time.perf_counter()
        worker = threading.Thread(target=_worker, name="fabric-peer-peek",
                                  daemon=True)
        worker.start()
        worker.join(self._peek_deadline_s)
        obs.histogram("cache_peek_s", time.perf_counter() - t0)
        out = cell[0] if cell else None
        if out is None or isinstance(out, Exception):
            # timeout, refused connection, chaos net_partition/net_hang —
            # all count against the peer's breaker
            obs.counter("cache_peek_timeouts")
            with self._peer_lock:
                self._peer_stats["peek_timeouts"] += 1
            self._record_peer_outcome(owner, ok=False)
            return None
        self._record_peer_outcome(owner, ok=True)
        with self._lock:
            gen = self.generation
        if out.get("hit") and out.get("generation") == gen:
            obs.counter("cache_peer_hits")
            with self._peer_lock:
                self._peer_stats["peer_hits"] += 1
            return ([float(s) for s in out["scores"]],
                    [int(d) for d in out["docs"]])
        obs.counter("cache_peer_misses")
        with self._peer_lock:
            self._peer_stats["peer_misses"] += 1
        return None

    def _enqueue_fill(self, owner: int, rid: str, terms, ranker: str,
                      scores, docs) -> None:
        with self._lock:
            gen = self.generation
        try:
            self._fill_q.put_nowait(
                (owner, rid, list(terms), ranker, scores, docs, gen))
        except queue.Full:
            with self._peer_lock:
                self._peer_stats["fills_dropped"] += 1

    def _fill_loop(self) -> None:
        while True:
            item = self._fill_q.get()
            if item is None or self._stop.is_set():
                return
            try:
                self._fill_owner(*item)
            except Exception:  # noqa: BLE001 — fills are best-effort
                obs.counter("cache_fill_errors")
                with self._peer_lock:
                    self._peer_stats["fill_errors"] += 1

    def _fill_owner(self, owner: int, rid: str, terms, ranker: str,
                    scores, docs, generation) -> None:
        """One asynchronous owner write-back (idempotent by rid)."""
        if self._breaker_for(owner) is None:
            with self._peer_lock:
                self._peer_stats["fills_skipped_open"] += 1
            return
        with self._peer_lock:
            port = self._peers.get(owner)
        if port is None:
            return
        doc = {"rid": rid, "terms": terms, "ranker": ranker,
               "scores": scores, "docs": docs, "generation": generation}
        try:
            resp = rx.attempt_once(
                lambda: self._peer_post(port, "/cache/fill", doc,
                                        self._peek_deadline_s),
                site=FILL_SITE,
            )
        except urllib.error.HTTPError:
            # typed rejection (e.g. 503 below-floor): the peer answered —
            # breaker stays healthy, the owner just stays cold
            self._record_peer_outcome(owner, ok=True)
            return
        except Exception:  # noqa: BLE001 — timeout/partition
            obs.counter("cache_fill_errors")
            with self._peer_lock:
                self._peer_stats["fill_errors"] += 1
            self._record_peer_outcome(owner, ok=False)
            return
        self._record_peer_outcome(owner, ok=True)
        if resp.get("stored"):
            obs.counter("cache_fills")
            with self._peer_lock:
                self._peer_stats["fills"] += 1

    def configure_peers(self, peers: "dict[int, int]", *,
                        slots: int = 64) -> None:
        """Install the fleet topology pushed by the router: id→port map
        and the cache-authority ring (all replica ids, self included, so
        every member routes a key to the SAME owner).  Existing breaker
        state survives a push — a roll must not reset trip history."""
        others = {i: p for i, p in peers.items() if i != self.replica_id}
        ids = sorted(set(peers) | {self.replica_id})
        with self._peer_lock:
            self._peers = others
            self._peer_ring = _Ring(ids, slots=slots) if len(ids) > 1 else None
            self._breakers = {
                i: self._breakers.get(i)
                or _Breaker(self._breaker_trip, self._breaker_probe_s)
                for i in others
            }
        obs.emit("cache_peers", replica=self.replica_id,
                 peers=sorted(others), slots=slots)

    # ----------------------------------------------------------- HTTP API

    def handle_query(self, body: bytes) -> tuple[int, str, str]:
        from page_rank_and_tfidf_using_apache_spark_tpu.serving.server import (
            ServerShutdown,
        )

        try:
            req = json.loads(body.decode("utf-8"))
            rid = str(req["rid"])
            terms = [str(t) for t in req["terms"]]
            ranker = str(req.get("ranker", "tfidf"))
        except (ValueError, KeyError, UnicodeDecodeError,
                TypeError, AttributeError) as exc:
            # TypeError/AttributeError: syntactically valid JSON of the
            # wrong SHAPE ([], null, a bare string) — a malformed message
            # must get a typed 400, never crash into the dispatcher's 500
            return (400, "application/json",
                    json.dumps({"error": f"bad request: {exc}"}))
        with self._lock:
            cached = self._rid_cache.get(rid)
            if cached is not None:
                self._rid_cache.move_to_end(rid)
                self._replays += 1
        if cached is not None:
            return cached  # idempotent replay: same bytes, no re-execution
        if not self.ready():
            with self._lock:
                gen, floor = self.generation, self.floor
            return (503, "application/json",
                    json.dumps({"error": "replica below generation floor",
                                "generation": gen, "floor": floor}))
        t0 = time.perf_counter()
        # Sharded-cache fast path (ISSUE 20): when another replica is the
        # ring authority for this key, consult the local LRU, then peek
        # the owner under a bounded deadline — and only then compute.
        # Every branch serves the SAME values (JSON round-trip exact), so
        # local hit / peer hit / fallback compute are byte-equal.
        owner = self._cache_owner(terms, ranker)
        served: "tuple[list, list] | None" = None
        if owner is not None and owner != self.replica_id:
            try:
                local = self.srv.cache_lookup(terms, ranker=ranker)
            except Exception:  # noqa: BLE001 — lookup is best-effort
                local = None
            if local is not None:
                served = ([float(s) for s in local[0]],
                          [int(d) for d in local[1]])
                with self._peer_lock:
                    self._peer_stats["nonowner_local_hits"] += 1
            else:
                served = self._peek_owner(owner, terms, ranker)
        if served is None:
            try:
                # ONE chaos-hooked execution (proc_kill here is the
                # replica-SIGKILL-mid-query scenario; the router's sibling
                # retry owns recovery)
                scores, docs = rx.attempt_once(
                    lambda: self.srv.query(terms, ranker=ranker),
                    site=QUERY_SITE,
                )
            except ServerShutdown as exc:
                return (503, "application/json",
                        json.dumps({"error": f"shutdown: {exc}"}))
            except ValueError as exc:  # unknown ranker / no BM25 weights
                return (400, "application/json",
                        json.dumps({"error": str(exc)}))
            served = ([float(s) for s in scores], [int(d) for d in docs])
            if owner is not None and owner != self.replica_id:
                # fill the authority back asynchronously (idempotent by
                # rid — a router re-dispatch fills once)
                self._enqueue_fill(owner, rid, terms, ranker,
                                   served[0], served[1])
        with self._lock:
            gen = self.generation
        resp = (200, "application/json", json.dumps({
            "rid": rid,
            "replica": self.replica_id,
            "generation": gen,
            "scores": served[0],
            "docs": served[1],
        }))
        with self._lock:
            self._executions += 1
            self._lat.append(time.perf_counter() - t0)
            self._rid_cache[rid] = resp
            while len(self._rid_cache) > self._rid_cap:
                self._rid_cache.popitem(last=False)
        return resp

    def handle_status(self, body: bytes) -> tuple[int, str, str]:
        with self._lock:
            gen, floor = self.generation, self.floor
            executions, replays = self._executions, self._replays
            p50, p99 = _percentiles_ms(self._lat)
        with self._peer_lock:
            peer = dict(self._peer_stats)
            breaker_open = sum(
                1 for b in self._breakers.values() if b.state != "closed")
        stats = dict(self.srv.stats()) if self.srv is not None else {}
        return (200, "application/json", json.dumps({
            "replica": self.replica_id,
            "pid": os.getpid(),
            "ready": self.ready(),
            "generation": gen,
            "floor": floor,
            "executions": executions,
            "replays": replays,
            "p50_ms": p50,
            "p99_ms": p99,
            "requests": int(stats.get("requests", 0)),
            "cache_hits": int(stats.get("cache_hits", 0)),
            "refreshes": int(stats.get("refreshes", 0)),
            "peer_hits": int(peer.get("peer_hits", 0)),
            "peer_misses": int(peer.get("peer_misses", 0)),
            "peek_timeouts": int(peer.get("peek_timeouts", 0)),
            "fills": int(peer.get("fills", 0)),
            "breaker_open": breaker_open,
            "peer_stores": int(stats.get("peer_stores", 0)),
        }))

    def handle_cache_peek(self, body: bytes) -> tuple[int, str, str]:
        """``POST /cache/peek`` — the cache-authority read path.  A pure
        lookup: a miss is a successful 200 with ``hit: false`` (the
        peeker falls back to computing), never an error; no side effects,
        so no rid and no idempotency machinery."""
        try:
            req = json.loads(body.decode("utf-8"))
            terms = [str(t) for t in req["terms"]]
            ranker = str(req.get("ranker", "tfidf"))
        except (ValueError, KeyError, UnicodeDecodeError,
                TypeError, AttributeError) as exc:
            return (400, "application/json",
                    json.dumps({"error": f"bad request: {exc}"}))
        with self._lock:
            gen = self.generation
        hit = None
        if self.srv is not None and self.ready():
            try:
                hit = self.srv.cache_lookup(terms, ranker=ranker)
            except Exception:  # noqa: BLE001 — lookup is best-effort
                hit = None
        if hit is None:
            return (200, "application/json",
                    json.dumps({"hit": False, "generation": gen}))
        return (200, "application/json", json.dumps({
            "hit": True,
            "generation": gen,
            "scores": [float(s) for s in hit[0]],
            "docs": [int(d) for d in hit[1]],
        }))

    def handle_cache_fill(self, body: bytes) -> tuple[int, str, str]:
        """``POST /cache/fill`` — the cache-authority write-back,
        idempotent by rid (a router re-dispatch of the originating query
        re-fills at most once: the replayed rid returns the SAME bytes
        without touching the cache again)."""
        try:
            req = json.loads(body.decode("utf-8"))
            rid = str(req["rid"])
            terms = [str(t) for t in req["terms"]]
            scores = [float(s) for s in req["scores"]]
            docs = [int(d) for d in req["docs"]]
            gen_in = int(req["generation"])
            ranker = str(req.get("ranker", "tfidf"))
        except (ValueError, KeyError, UnicodeDecodeError,
                TypeError, AttributeError) as exc:
            return (400, "application/json",
                    json.dumps({"error": f"bad request: {exc}"}))
        fill_key = "fill:" + rid  # namespaced: never collides with /query
        with self._lock:
            cached = self._rid_cache.get(fill_key)
            if cached is not None:
                self._rid_cache.move_to_end(fill_key)
                self._replays += 1
        if cached is not None:
            return cached
        if not self.ready():
            with self._lock:
                gen, floor = self.generation, self.floor
            return (503, "application/json",
                    json.dumps({"error": "replica below generation floor",
                                "generation": gen, "floor": floor}))
        with self._lock:
            gen = self.generation
        stored = False
        if gen_in == gen:
            # only same-generation fills are authoritative: a straggler
            # fill from before a hot-swap must not resurrect stale scores
            try:
                stored = bool(self.srv.cache_insert(
                    terms, scores, docs, ranker=ranker))
            except Exception:  # noqa: BLE001 — insert is best-effort
                stored = False
        resp = (200, "application/json", json.dumps({
            "stored": stored,
            "replica": self.replica_id,
            "generation": gen,
        }))
        with self._lock:
            self._rid_cache[fill_key] = resp
            while len(self._rid_cache) > self._rid_cap:
                self._rid_cache.popitem(last=False)
        return resp

    def handle_peers(self, body: bytes) -> tuple[int, str, str]:
        """``POST /peers`` — router pushes the fleet topology (id→port)
        after every membership change; idempotent by construction."""
        try:
            req = json.loads(body.decode("utf-8"))
            peers = {int(k): int(v) for k, v in req["peers"].items()}
            slots = int(req.get("slots", 64))
        except (ValueError, KeyError, UnicodeDecodeError,
                TypeError, AttributeError) as exc:
            return (400, "application/json",
                    json.dumps({"error": f"bad request: {exc}"}))
        self.configure_peers(peers, slots=slots)
        return (200, "application/json",
                json.dumps({"ok": True, "peers": len(peers)}))


def replica_main(argv: "list[str] | None" = None) -> int:
    """``--replica`` process entry: serve one replica until SIGTERM.

    Prints the one-line JSON ready handshake (port, pid, generation) on
    stdout once the HTTP surface is up — possibly *unready* below the
    floor; readiness is the router's business via /healthz.  Runs under
    ``obs.run`` so the replica writes its own trace and adopts
    ``GRAFT_TRACE_PARENT`` — the fleet stitches into one trace tree."""
    p = argparse.ArgumentParser(prog="fabric-replica")
    p.add_argument("index")
    p.add_argument("--replica-id", type=int, default=0)
    p.add_argument("--port", type=int, default=0)
    p.add_argument("--top-k", type=int, default=10)
    p.add_argument("--max-batch", type=int, default=None)
    p.add_argument("--scoring", choices=["coo", "impacted"], default="coo")
    p.add_argument("--poll-s", type=float, default=0.3)
    p.add_argument("--metrics-window-s", type=float, default=60.0)
    p.add_argument("--latency-slo-s", type=float, default=None)
    p.add_argument("--availability-target", type=float, default=None)
    # --reuse-port: join the SO_REUSEPORT listener group on --port AND
    # drain in-flight requests on SIGTERM — the predecessor/successor
    # sides of the zero-downtime handoff (ISSUE 20).  --ready-at-floor
    # defers the stdout handshake until ready(): the router's spawn()
    # blocks on the handshake, so a handoff successor signals "healthy"
    # through the SAME mechanism that already guards against leaked
    # children.  --cache-size bounds the server LRU (bench A/B).
    p.add_argument("--reuse-port", action="store_true")
    p.add_argument("--ready-at-floor", action="store_true")
    p.add_argument("--cache-size", type=int, default=None)
    args = p.parse_args(argv)

    stop = threading.Event()

    def _on_sigterm(signum, frame):
        stop.set()

    signal.signal(signal.SIGTERM, _on_sigterm)

    with obs.run(f"fabric-replica{args.replica_id}"):
        rep = _Replica(args.index, replica_id=args.replica_id,
                       top_k=args.top_k, max_batch=args.max_batch,
                       scoring=args.scoring, poll_s=args.poll_s,
                       cache_size=args.cache_size).start()
        # the replica's OWN hub, not the lazy process default: windowed
        # to the fleet's merge window and carrying the router-declared
        # SLO budgets, so what this replica exports is federable and its
        # burn rate is measured where the requests are actually served
        hub = MetricsHub(window_s=args.metrics_window_s,
                         latency_slo_s=args.latency_slo_s,
                         availability_target=args.availability_target)
        sink = TelemetrySink(hub)
        obs.bus().attach(sink)
        exporter = obs.export.MetricsExporter(
            hub, port=args.port,
            routes={("POST", "/query"): rep.handle_query,
                    ("GET", "/status"): rep.handle_status,
                    ("POST", "/cache/peek"): rep.handle_cache_peek,
                    ("POST", "/cache/fill"): rep.handle_cache_fill,
                    ("POST", "/peers"): rep.handle_peers},
            ready=rep.ready,
            reuse_port=args.reuse_port, drain=args.reuse_port,
        ).start()
        # handoff successor: hold the handshake until this process could
        # actually serve — the router treats handshake == /healthz-green
        # and only then SIGTERMs the predecessor
        while args.ready_at_floor and not rep.ready() and not stop.is_set():
            time.sleep(args.poll_s)
        print(json.dumps({"ready": True, "port": exporter.port,
                          "pid": os.getpid(),
                          "generation": rep.generation}), flush=True)
        try:
            stop.wait()
        finally:
            # graceful: stop accepting (HTTP down), then drain the server
            # — with --reuse-port the exporter BLOCKS here until every
            # in-flight handler thread has answered (the predecessor side
            # of the handoff: the kernel already steers new connections
            # to the successor, so draining loses nothing); without it,
            # still-pending futures fail typed (ServerShutdown) and the
            # router re-dispatches them on a sibling
            t_drain = time.perf_counter()
            obs.emit("fabric_drain_begin", replica=args.replica_id,
                     pid=os.getpid(), handoff=bool(args.reuse_port))
            exporter.stop()
            drain_s = time.perf_counter() - t_drain
            obs.histogram("fabric_drain_s", drain_s)
            obs.emit("fabric_drain_done", replica=args.replica_id,
                     pid=os.getpid(), drain_s=round(drain_s, 6))
            rep.stop()
            obs.bus().detach(sink)
    return 0


# --------------------------------------------------------------- router


class ServingFabric:
    """Router + supervisor over N replica processes (see module doc)."""

    def __init__(self, index_dir: str, cfg: FabricConfig = FabricConfig()):
        self.index_dir = index_dir
        self.cfg = cfg
        # Membership is DYNAMIC (ISSUE 19): id-keyed maps instead of
        # fixed-size lists, so scale_up/scale_down change the fleet while
        # the ring keeps survivor-owned keys in place (a newcomer gets a
        # fresh id; the newest id drains first).
        self._handles: dict[int, procs.ProcessHandle] = {}
        self._ports: dict[int, int] = {}
        self._next_id = cfg.replicas
        self._suspect: set[int] = set()
        self._restarting: set[int] = set()
        # ids mid-drain-handoff: the supervisor must NOT respawn a
        # predecessor that dies inside the handoff window (the swap
        # would orphan the respawn — two listeners on one port), but
        # unlike _restarting the id stays in routing rotation: the
        # whole point of the handoff is that it never stops serving
        self._handoff_ids: set[int] = set()
        self._down_since: dict[int, float] = {}
        self._ring = _Ring(range(cfg.replicas), cfg.ring_slots)
        self._lock = threading.Lock()  # membership/ports/suspects/audit/stats
        self._stats: collections.Counter = collections.Counter()
        self._audit: dict[str, int] = {}  # rid -> accepted deliveries
        # Drain-handoff state (ISSUE 20): per-id "anchor" sockets — bound
        # with SO_REUSEPORT but never listening — pin each replica's port
        # across respawns and rolls so a successor can join the listener
        # group on the SAME address while the predecessor drains.
        # _roll_active > 0 while rolling_restart runs: retries taken in
        # that window are roll-attributed (the handoff acceptance gate
        # requires that count to stay 0).
        self._anchors: dict[int, socket.socket] = {}
        self._roll_active = 0
        self._rid_seq = itertools.count()
        self._rid_prefix = f"f{os.getpid()}-{int(time.time() * 1e3) & 0xFFFFFF}"
        self._stop = threading.Event()
        self._health_thread: threading.Thread | None = None
        self._sup_thread: threading.Thread | None = None
        self._started = False
        # The fleet observability plane: scrape-and-merge hub + the
        # router's own metrics endpoint (both None when federation=False).
        self.fleet: FleetHub | None = None
        self._fleet_exporter = None
        if cfg.federation:
            self.fleet = FleetHub(
                window_s=cfg.fleet_window_s,
                latency_slo_s=cfg.latency_slo_s,
                availability_target=cfg.availability_target,
            )

    # ----------------------------------------------------------- lifecycle

    def _handoff_enabled(self) -> bool:
        """Drain handoff needs SO_REUSEPORT; without it (or with
        cfg.handoff off) rolls fall back to the PR-17 retry-carried
        path."""
        return self.cfg.handoff and obs.export.reuse_port_supported()

    def _fixed_port(self, i: int) -> int:
        """The pinned port for replica ``i``, reserved by an anchor
        socket that joins the SO_REUSEPORT group but never listens (so
        the kernel steers zero connections to it).  Created on first use,
        held until the id leaves the fleet — respawns and handoff
        successors all bind the same address."""
        with self._lock:
            anchor = self._anchors.get(i)
            if anchor is None:
                anchor = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
                anchor.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEPORT, 1)
                anchor.bind(("127.0.0.1", 0))
                self._anchors[i] = anchor
            return int(anchor.getsockname()[1])

    def _close_anchor(self, i: int) -> None:
        with self._lock:
            anchor = self._anchors.pop(i, None)
        if anchor is not None:
            try:
                anchor.close()
            except OSError:
                pass

    def _replica_argv(self, i: int) -> list[str]:
        if self._handoff_enabled():
            port_args = ["--port", str(self._fixed_port(i)), "--reuse-port"]
        else:
            port_args = ["--port", "0"]
        argv = [sys.executable, "-m",
                "page_rank_and_tfidf_using_apache_spark_tpu.serving.fabric",
                "--replica", self.index_dir,
                "--replica-id", str(i),
                *port_args,
                "--top-k", str(self.cfg.top_k),
                "--scoring", self.cfg.scoring,
                "--poll-s", str(self.cfg.poll_s)]
        if self.cfg.cache_size is not None:
            argv += ["--cache-size", str(self.cfg.cache_size)]
        if self.cfg.max_batch is not None:
            argv += ["--max-batch", str(self.cfg.max_batch)]
        if self.cfg.federation:
            # the replica hub must share the fleet's merge window (the
            # mergeable wire format rejects mismatched windows) and carry
            # the SAME SLO budgets — replica-side budgets are what make
            # the federated burn rate a real measured autoscale signal
            # instead of a constant zero
            argv += ["--metrics-window-s", str(self.cfg.fleet_window_s)]
            if self.cfg.latency_slo_s is not None:
                argv += ["--latency-slo-s", str(self.cfg.latency_slo_s)]
            if self.cfg.availability_target is not None:
                argv += ["--availability-target",
                         str(self.cfg.availability_target)]
        return argv

    def _replica_env(self, i: int) -> dict[str, str]:
        env = procs.fabric_pgid_env()  # parent chaos plan never leaks in
        for idx, spec in self.cfg.replica_chaos:
            if idx == i:
                env["GRAFT_CHAOS"] = spec
        return env

    def _spawn(self, i: int, *,
               ready_at_floor: bool = False) -> procs.ProcessHandle:
        argv = self._replica_argv(i)
        if ready_at_floor:
            # handoff successor: spawn() blocking on the handshake now
            # doubles as the /healthz wait — the handshake only prints
            # once the successor would answer ready
            argv = argv + ["--ready-at-floor"]
        handle = procs.ProcessHandle(
            argv, env=self._replica_env(i),
            ready_timeout_s=self.cfg.ready_timeout_s,
        ).spawn()
        obs.emit("fabric_spawn", replica=i, pid=handle.pid,
                 port=handle.ready.get("port"),
                 generation=handle.ready.get("generation"))
        return handle

    def _push_peers(self) -> None:
        """Push the fleet topology (id→port) to every replica so each
        can route cache authority; called after every membership change.
        Best-effort: a replica that misses a push just keeps its previous
        view until the next one."""
        if not self.cfg.peer_cache:
            return
        with self._lock:
            ports = dict(self._ports)
        doc = {"peers": {str(i): p for i, p in ports.items()},
               "slots": self.cfg.ring_slots}
        for i in sorted(ports):
            try:
                self._post_json(i, "/peers", doc, 2.0)
            except Exception:  # noqa: BLE001 — replica catches next push
                with self._lock:
                    self._stats["peer_push_errors"] += 1

    def _register_with_fleet(self, i: int, port: int) -> None:
        if self.fleet is not None:
            self.fleet.register(str(i), f"http://127.0.0.1:{port}")

    def start(self) -> "ServingFabric":
        if self._started:
            return self
        obs.emit("fabric_start", replicas=self.cfg.replicas,
                 ring_slots=self.cfg.ring_slots, index_dir=self.index_dir)
        for i in range(self.cfg.replicas):
            handle = self._spawn(i)
            port = int(handle.ready["port"])
            with self._lock:
                self._handles[i] = handle
                self._ports[i] = port
            self._register_with_fleet(i, port)
        self._push_peers()
        if self.fleet is not None:
            self.fleet.start()
            self._fleet_exporter = obs.export.MetricsExporter(
                self.fleet, port=0).start()
            obs.emit("fabric_fleet_export", url=self._fleet_exporter.url,
                     replicas=len(self._handles))
        self._started = True
        self._health_thread = threading.Thread(
            target=self._health_loop, name="fabric-health", daemon=True
        )
        self._health_thread.start()
        self._sup_thread = threading.Thread(
            target=self._supervise_loop, name="fabric-supervisor", daemon=True
        )
        self._sup_thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        for t in (self._health_thread, self._sup_thread):
            if t is not None:
                t.join(timeout=10.0)
        self._health_thread = self._sup_thread = None
        if self._fleet_exporter is not None:
            self._fleet_exporter.stop()
            self._fleet_exporter = None
        if self.fleet is not None:
            self.fleet.stop()
        with self._lock:
            handles = list(self._handles.values())
        for handle in handles:
            handle.terminate(self.cfg.grace_s)
        with self._lock:
            anchors, self._anchors = dict(self._anchors), {}
        for anchor in anchors.values():
            try:
                anchor.close()
            except OSError:
                pass
        obs.emit("fabric_stop", **self.audit())
        self._started = False

    @property
    def fleet_url(self) -> str | None:
        """The router's own metrics endpoint (fleet /snapshot.json +
        /metrics), None until started or with federation off."""
        ex = self._fleet_exporter
        return None if ex is None else ex.url

    def __enter__(self) -> "ServingFabric":
        return self.start()

    def __exit__(self, *exc: object) -> None:
        self.stop()

    # ----------------------------------------------------------- plumbing

    def _url(self, i: int, path: str) -> str:
        with self._lock:
            port = self._ports.get(i)
        if port is None:  # drained between route and dispatch: retry path
            raise KeyError(f"replica {i} left the fleet")
        return f"http://127.0.0.1:{port}{path}"

    def replica_ids(self) -> list[int]:
        """The live fleet, sorted (membership snapshot under the lock)."""
        with self._lock:
            return sorted(self._handles)

    def _get_json(self, i: int, path: str, timeout: float) -> dict:
        with urllib.request.urlopen(self._url(i, path),
                                    timeout=timeout) as r:
            return json.loads(r.read().decode("utf-8"))

    def _post_json(self, i: int, path: str, doc: dict,
                   timeout: float) -> dict:
        req = urllib.request.Request(
            self._url(i, path), data=json.dumps(doc).encode("utf-8"),
            headers={"Content-Type": "application/json"}, method="POST",
        )
        with urllib.request.urlopen(req, timeout=timeout) as r:
            return json.loads(r.read().decode("utf-8"))

    # ----------------------------------------------------------- queries

    def query(self, terms: Sequence[str], *, ranker: str = "tfidf",
              timeout: float | None = None) -> tuple[np.ndarray, np.ndarray]:
        """Route one query; on replica failure re-dispatch to the next
        sibling on the ring under the SAME request id.  Raises
        :class:`FabricExhausted` past the retry budget — callers see a
        served answer or a typed refusal, never a silent drop."""
        rid = f"{self._rid_prefix}-{next(self._rid_seq)}"
        key = affinity_key(terms, ranker)
        with self._lock:
            self._stats["requests"] += 1
            self._audit[rid] = 0
        deadline = None if timeout is None else time.monotonic() + timeout
        last_err: str | None = None
        for attempt in range(self.cfg.retry_limit):
            if deadline is not None and time.monotonic() > deadline:
                break
            with self._lock:
                avoid = self._suspect | self._restarting
            order = self._ring.route(key, exclude=avoid)
            # rotate across the HEALTHY candidates (suspects sit at the
            # back of `order`): a hop that just partitioned must not be
            # the very next target; with the whole fleet suspect, rotate
            # over everyone — the supervisor may be respawning them
            pool = [r for r in order if r not in avoid] or order
            target = pool[attempt % len(pool)]
            try:
                # one chaos-hooked hop: net_partition / net_hang / fail
                # at this site fault the router→replica link
                resp = rx.attempt_once(
                    lambda: self._post_json(
                        target, "/query",
                        {"rid": rid, "terms": list(terms), "ranker": ranker},
                        self.cfg.request_timeout_s,
                    ),
                    site=ROUTE_SITE,
                )
            except chaos.PartitionError as exc:
                self._mark_suspect(target, f"partition: {exc}")
                last_err = str(exc)
                continue
            except urllib.error.HTTPError as exc:
                if exc.code == 400:
                    body = exc.read().decode("utf-8", "replace")
                    try:
                        msg = json.loads(body).get("error", body)
                    except json.JSONDecodeError:
                        msg = body
                    raise ValueError(msg) from exc
                # 503 = below floor / shutting down: not suspect-worthy
                # on its own (the poll loop will catch it up) — just try
                # a sibling and come back later
                last_err = f"HTTP {exc.code}"
                with self._lock:
                    self._stats["retries"] += 1
                    if self._roll_active:
                        self._stats["roll_retries"] += 1
                time.sleep(self.cfg.retry_pause_s)
                continue
            except Exception as exc:  # noqa: BLE001 — dead/hung replica
                self._mark_suspect(target, f"{type(exc).__name__}: {exc}")
                last_err = f"{type(exc).__name__}: {exc}"
                with self._lock:
                    self._stats["retries"] += 1
                    if self._roll_active:
                        self._stats["roll_retries"] += 1
                time.sleep(self.cfg.retry_pause_s)
                continue
            with self._lock:
                self._audit[rid] += 1
                self._stats["delivered"] += 1
                self._suspect.discard(target)
            return (np.asarray(resp["scores"], dtype=np.float32),
                    np.asarray(resp["docs"], dtype=np.int32))
        with self._lock:
            self._stats["failed"] += 1
        raise FabricExhausted(
            f"query {rid} undeliverable after {self.cfg.retry_limit} "
            f"attempts (last: {last_err})"
        )

    def _mark_suspect(self, i: int, why: str) -> None:
        with self._lock:
            fresh = i not in self._suspect
            self._suspect.add(i)
        if fresh:
            obs.emit("fabric_suspect", replica=i, error=why[:200])

    # ----------------------------------------------------------- health

    def _health_loop(self) -> None:
        while not self._stop.wait(self.cfg.health_period_s):
            for i in self.replica_ids():
                with self._lock:
                    if i in self._restarting or i not in self._handles:
                        continue
                try:
                    status = self._get_json(i, "/status", timeout=2.0)
                    healthy = bool(status.get("ready"))
                except Exception:  # noqa: BLE001 — unreachable = unhealthy
                    status, healthy = None, False
                with self._lock:
                    was = i not in self._suspect
                    if healthy:
                        self._suspect.discard(i)
                    else:
                        self._suspect.add(i)
                if healthy != was:
                    obs.emit("fabric_health", replica=i, healthy=healthy)
                if status is not None:
                    # per-replica metrics fold: the fleet's numbers land
                    # in the ROUTER's trace + hub, one gauge per replica
                    obs.emit("fabric_replica_stats", replica=i,
                             requests=status.get("requests"),
                             executions=status.get("executions"),
                             replays=status.get("replays"),
                             p50_ms=status.get("p50_ms"),
                             p99_ms=status.get("p99_ms"),
                             generation=status.get("generation"),
                             floor=status.get("floor"),
                             cache_hits=status.get("cache_hits"),
                             peer_hits=status.get("peer_hits"),
                             peer_misses=status.get("peer_misses"),
                             peek_timeouts=status.get("peek_timeouts"),
                             fills=status.get("fills"),
                             breaker_open=status.get("breaker_open"),
                             peer_stores=status.get("peer_stores"))
                    obs.gauge(f"fabric_replica{i}_requests",
                              float(status.get("requests") or 0))

    # ----------------------------------------------------------- supervisor

    def _supervise_loop(self) -> None:
        while not self._stop.wait(self.cfg.poll_s):
            for i in self.replica_ids():
                with self._lock:
                    if i in self._restarting or i in self._handoff_ids:
                        continue
                    handle = self._handles.get(i)
                if handle is None:  # drained since the snapshot
                    continue
                if handle.alive():
                    with self._lock:
                        self._down_since.pop(i, None)
                    continue
                if not self.cfg.respawn:
                    self._mark_suspect(i, "dead (respawn disabled)")
                    continue
                with self._lock:
                    t_down = self._down_since.setdefault(i, time.monotonic())
                try:
                    fresh = procs.respawn(
                        handle, site=ROUTE_SITE, replica=i,
                        spawn=lambda: self._spawn(i),
                    )
                except procs.ProcessSpawnError as exc:
                    self._mark_suspect(i, f"respawn failed: {exc}")
                    continue
                recovery_s = time.monotonic() - t_down
                port = int(fresh.ready["port"])
                with self._lock:
                    if i not in self._handles:  # drained mid-respawn
                        fresh.terminate(self.cfg.grace_s)
                        continue
                    self._handles[i] = fresh
                    self._ports[i] = port
                    self._suspect.discard(i)
                    self._down_since.pop(i, None)
                    self._stats["respawns"] += 1
                self._register_with_fleet(i, port)  # fresh ephemeral port
                obs.emit("fabric_respawn", replica=i, pid=fresh.pid,
                         port=fresh.ready.get("port"),
                         recovery_s=round(recovery_s, 3))
                self._push_peers()  # respawn may have moved the port

    # ----------------------------------------------------------- fleet ops

    def statuses(self, timeout: float = 2.0) -> list[dict | None]:
        out: list[dict | None] = []
        for i in self.replica_ids():
            try:
                out.append(self._get_json(i, "/status", timeout=timeout))
            except Exception:  # noqa: BLE001 — down replica = None
                out.append(None)
        return out

    def fleet_generation(self) -> int | None:
        """The fleet's servable generation: min over ready replicas
        (None when no replica is ready)."""
        gens = [s["generation"] for s in self.statuses()
                if s is not None and s.get("ready")]
        return min(gens) if gens else None

    def await_fleet_generation(self, generation: int,
                               timeout: float = 60.0) -> bool:
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            statuses = self.statuses()
            if all(s is not None and s.get("ready")
                   and (s.get("generation") or 0) >= generation
                   for s in statuses):
                return True
            time.sleep(self.cfg.poll_s)
        return False

    def rolling_restart(self, *, generation: int | None = None,
                        timeout: float = 120.0) -> None:
        """Roll the fleet one replica at a time under a committed floor:
        (1) wait until EVERY replica serves ≥ G, (2) durably commit the
        floor at G — from here no replica may come back below it —
        (3) replace each replica while its siblings keep serving.

        With handoff enabled (ISSUE 20) a replica is replaced by spawning
        its successor into the SAME SO_REUSEPORT listener group FIRST,
        blocking until the successor's deferred handshake (== healthy at
        ≥ G), and only then TERMing the predecessor, which stops
        accepting and drains its in-flight requests to completion — the
        kernel steers every new connection to the successor throughout,
        so the roll needs zero sibling retries and the replica never
        leaves the routing ring.  Without SO_REUSEPORT (or with
        cfg.handoff off) the PR-17 path runs: TERM → respawn →
        wait-ready, with in-flight queries failing typed (ServerShutdown
        → HTTP 503) and re-dispatching to siblings under their original
        request ids."""
        from page_rank_and_tfidf_using_apache_spark_tpu.serving import (
            segments as sgm,
        )

        G = generation
        if G is None:
            G = sgm.manifest_version(self.index_dir) or 0
        if not self.await_fleet_generation(G, timeout=timeout):
            raise TimeoutError(
                f"fleet never reached generation {G} within {timeout}s"
            )
        commit_floor(self.index_dir, G)
        live = self.replica_ids()
        handoff = self._handoff_enabled()
        obs.emit("fabric_roll_start", floor=G, replicas=len(live),
                 handoff=handoff)
        with self._lock:
            self._roll_active += 1
        try:
            for i in live:
                if handoff:
                    self._handoff_replica(i, G)
                else:
                    self._roll_replica_retry(i, G, timeout)
        finally:
            with self._lock:
                self._roll_active -= 1
        obs.emit("fabric_roll_done", floor=G, handoff=handoff)

    def _handoff_replica(self, i: int, G: int) -> None:
        """One zero-downtime replacement: successor first, drain second.

        Kill-point discipline: SIGKILL anywhere in this window leaves
        exactly one generation serving — before the spawn returns, the
        predecessor still owns the port (a dead half-spawned successor
        never printed its handshake, and ProcessHandle's spawn timeout
        reaps it); after the swap, the successor owns it and a killed
        predecessor just cuts its drain short (its in-flight requests
        fail typed into the sibling-retry path, same rid)."""
        with self._lock:
            old = self._handles.get(i)
            if old is None:  # drained while the roll was in flight
                return
            # suppress supervisor respawn for the window: a predecessor
            # SIGKILLed mid-handoff must be REPLACED by the swap below,
            # not raced by a second spawn onto the same port — unlike
            # _restarting the id stays in routing rotation (the handoff
            # never stops serving)
            self._handoff_ids.add(i)
        t0 = time.monotonic()
        try:
            obs.emit("fabric_handoff", replica=i, phase="spawn", floor=G)
            # ONE chaos-hooked successor spawn (fail/proc_kill here is
            # the successor-dies-mid-handoff scenario): on failure the
            # predecessor is untouched and still serving — the roll
            # aborts with the fleet intact
            fresh = rx.attempt_once(
                lambda: self._spawn(i, ready_at_floor=True),
                site=DRAIN_SITE)
            obs.emit("fabric_handoff", replica=i, phase="successor_ready",
                     pid=fresh.pid, floor=G)
            with self._lock:
                if i not in self._handles:  # drained mid-handoff
                    fresh.terminate(self.cfg.grace_s)
                    return
                self._handles[i] = fresh
                # port unchanged (the anchor pins it) — no ring or fleet
                # registration churn; the replica never left rotation
            self._register_with_fleet(i, int(fresh.ready["port"]))
            obs.emit("fabric_handoff", replica=i, phase="drain",
                     pid=old.pid)
            old.terminate(self.cfg.grace_s)  # SIGTERM → drain → exit
        finally:
            with self._lock:
                self._handoff_ids.discard(i)
        handoff_s = time.monotonic() - t0
        obs.histogram("fabric_handoff_s", handoff_s)
        with self._lock:
            self._stats["rolled"] += 1
        obs.emit("fabric_rolled", replica=i, floor=G, handoff=True,
                 restart_s=round(handoff_s, 3))
        self._push_peers()

    def _roll_replica_retry(self, i: int, G: int, timeout: float) -> None:
        """The PR-17 retry-carried replacement (no SO_REUSEPORT)."""
        with self._lock:
            old = self._handles.get(i)
            if old is None:  # drained while the roll was in flight
                return
            self._restarting.add(i)
            self._suspect.add(i)  # route around it immediately
        t0 = time.monotonic()
        old.terminate(self.cfg.grace_s)
        fresh = self._spawn(i)
        port = int(fresh.ready["port"])
        with self._lock:
            self._handles[i] = fresh
            self._ports[i] = port
        self._register_with_fleet(i, port)
        # back in rotation only once it serves ≥ the floor
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            try:
                s = self._get_json(i, "/status", timeout=2.0)
                if s.get("ready") and (s.get("generation") or 0) >= G:
                    break
            except Exception:  # noqa: BLE001 — still coming up
                pass
            time.sleep(self.cfg.poll_s)
        else:
            raise TimeoutError(
                f"replica {i} never reached floor {G} after restart"
            )
        with self._lock:
            self._restarting.discard(i)
            self._suspect.discard(i)
            self._stats["rolled"] += 1
        obs.emit("fabric_rolled", replica=i, floor=G, handoff=False,
                 restart_s=round(time.monotonic() - t0, 3))
        self._push_peers()

    def kill_replica(self, i: int) -> int | None:
        """SIGKILL replica ``i`` (the bench/soak chaos hook); returns the
        killed pid.  The supervisor detects and respawns it."""
        handle = self._handles[i]
        pid = handle.pid
        handle.kill()
        obs.emit("fabric_kill", replica=i, pid=pid)
        return pid

    # ----------------------------------------------------------- scaling

    def _rebuild_ring_locked(self) -> None:
        self._ring = _Ring(sorted(self._handles), self.cfg.ring_slots)

    def scale_up(self, n: int = 1) -> list[int]:
        """Add ``n`` replicas under FRESH ids: the ring only gains vnodes,
        so every key owned by a survivor keeps its owner (the churn
        stability property) and only ~1/N of keys move to each newcomer.
        Reuses the exact spawn/handshake machinery of start()/respawn."""
        added: list[int] = []
        for _ in range(max(0, n)):
            with self._lock:
                i = self._next_id
                self._next_id += 1
            handle = self._spawn(i)
            port = int(handle.ready["port"])
            with self._lock:
                self._handles[i] = handle
                self._ports[i] = port
                self._rebuild_ring_locked()
                self._stats["scale_ups"] += 1
            self._register_with_fleet(i, port)
            added.append(i)
        if added:
            self._push_peers()
        return added

    def scale_down(self, n: int = 1) -> list[int]:
        """Drain the ``n`` newest replicas, never below one: a draining
        replica leaves the ring FIRST (no new queries route to it), its
        in-flight queries finish or fail typed into the sibling-retry
        path (same rid — the dropped=0/double_served=0 audit holds across
        every scale event), and only then is the process TERMed."""
        removed: list[int] = []
        for _ in range(max(0, n)):
            with self._lock:
                ids = sorted(self._handles)
                if len(ids) <= 1:
                    break
                i = ids[-1]
                handle = self._handles.pop(i)
                self._ports.pop(i, None)
                self._suspect.discard(i)
                self._restarting.discard(i)
                self._down_since.pop(i, None)
                self._rebuild_ring_locked()
                self._stats["scale_downs"] += 1
            if self.fleet is not None:
                self.fleet.deregister(str(i))
            # with handoff enabled the TERM drains in-flight requests to
            # completion before exit (the replica already left the ring,
            # so no NEW queries land on it meanwhile)
            handle.terminate(self.cfg.grace_s)
            self._close_anchor(i)
            obs.emit("fabric_drain", replica=i, pid=handle.pid)
            removed.append(i)
        if removed:
            self._push_peers()
        return removed

    def scale_to(self, n: int) -> None:
        cur = len(self.replica_ids())
        if n > cur:
            self.scale_up(n - cur)
        elif n < cur:
            self.scale_down(cur - n)

    def audit(self) -> dict:
        """The router-side delivery audit: requests / delivered / failed
        (= dropped candidates) / retries / respawns, plus double_served =
        request ids with more than one accepted delivery (structurally 0:
        the retry loop stops at the first success, and replicas replay —
        not re-execute — a duplicate rid)."""
        with self._lock:
            # Counter semantics drop zero-valued keys; the audit's keys
            # are ALWAYS present so callers (and diffs) never KeyError
            out = {k: int(self._stats.get(k, 0))
                   for k in ("requests", "delivered", "retries", "failed",
                             "respawns", "rolled", "scale_ups",
                             "scale_downs", "roll_retries")}
            out["dropped"] = out["failed"]
            out["double_served"] = sum(
                1 for n in self._audit.values() if n > 1
            )
        return out


# ------------------------------------------------------------ autoscaler


class Autoscaler:
    """Burn-rate replica autoscaling — the ROADMAP fabric follow-on.

    Reads ONLY the fleet hub (the same aggregate an operator sees at the
    router's ``/snapshot.json``): availability/latency budget burn and
    queue-wait p99 are the scale-up signals, sustained idle the
    scale-down signal.  Actions go through the fabric's own
    scale_up/scale_down (the supervisor's spawn/drain machinery), bounded
    by ``[min_replicas, max_replicas]``, rate-limited by ``cooldown_s``
    and hysteretic by config (see :class:`AutoscaleConfig`).

    Every decision is published as an ``autoscale`` event carrying its
    measured inputs — burn rates, queue p99, offered rate, fleet size
    before/after and the triggering reason — so tools/trace_report.py
    renders the scaling timeline and tools/trace_diff.py gates on flap
    count (a direction reversal between consecutive actions)."""

    def __init__(self, fabric: ServingFabric,
                 cfg: AutoscaleConfig = AutoscaleConfig(), *,
                 clock=time.monotonic):
        if fabric.fleet is None:
            raise ValueError("Autoscaler needs a fabric with federation=True")
        self.fabric = fabric
        self.cfg = cfg
        self._clock = clock
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None
        self._last_action_t: float | None = None
        self._idle_since: float | None = None
        self._decisions = 0
        self._ups = 0
        self._downs = 0
        self._flaps = 0
        self._last_dir: str | None = None

    def start(self) -> "Autoscaler":
        if self._thread is None:
            self._stop.clear()
            self._thread = threading.Thread(
                target=self._loop, name="fabric-autoscaler", daemon=True)
            self._thread.start()
            obs.emit("autoscale_start",
                     min_replicas=self.cfg.min_replicas,
                     max_replicas=self.cfg.max_replicas,
                     cooldown_s=self.cfg.cooldown_s)
        return self

    def stop(self) -> None:
        self._stop.set()
        t, self._thread = self._thread, None
        if t is not None:
            t.join(timeout=10.0)

    def __enter__(self) -> "Autoscaler":
        return self.start()

    def __exit__(self, *exc: object) -> None:
        self.stop()

    def _loop(self) -> None:
        while not self._stop.wait(self.cfg.period_s):
            try:
                self.tick()
            except Exception as exc:  # noqa: BLE001 — a bad tick skips, never kills
                obs.emit("autoscale_error",
                         error=f"{type(exc).__name__}: {exc}"[:200])

    @staticmethod
    def _measure(snap: dict) -> dict:
        """The decision inputs, extracted once so the emitted event and
        the decision logic can never disagree on what was measured."""
        budgets = snap.get("budgets") or {}
        qwin = snap.get("queue_wait_s") or {}
        ctr = snap.get("counters") or {}
        q_p99 = qwin.get("p99")
        return {
            "burn_availability": (budgets.get("availability") or {}).get(
                "burn_rate", 0.0),
            "burn_latency": (budgets.get("latency") or {}).get(
                "burn_rate", 0.0),
            "queue_p99_ms": (None if q_p99 is None
                             else round(float(q_p99) * 1e3, 3)),
            "rate_per_s": (ctr.get("serve.requests") or {}).get(
                "rate_per_s", 0.0),
        }

    def tick(self, snap: "dict | None" = None) -> str:
        """One control-loop evaluation (injectable snapshot for tests and
        the CI forced-decision smoke); returns the action taken:
        ``"up"``, ``"down"``, or ``"hold"``."""
        fleet = self.fabric.fleet
        assert fleet is not None  # checked at construction
        if snap is None:
            snap = fleet.snapshot()
        m = self._measure(snap)
        n = len(self.fabric.replica_ids())
        now = self._clock()
        self._decisions += 1

        burn = max(float(m["burn_availability"]), float(m["burn_latency"]))
        q_hot = (m["queue_p99_ms"] is not None
                 and m["queue_p99_ms"] >= self.cfg.queue_p99_up_s * 1e3)
        pressure = burn >= self.cfg.burn_up or q_hot
        idle = (float(m["rate_per_s"]) <= self.cfg.idle_rate_down
                and burn < self.cfg.burn_down)
        if idle:
            if self._idle_since is None:
                self._idle_since = now
        else:
            self._idle_since = None
        idle_held = (self._idle_since is not None
                     and now - self._idle_since >= self.cfg.idle_hold_s)
        cooling = (self._last_action_t is not None
                   and now - self._last_action_t < self.cfg.cooldown_s)

        action, reason = "hold", "steady"
        if pressure and cooling:
            reason = "cooldown"
        elif pressure and n >= self.cfg.max_replicas:
            reason = "at_max"
        elif pressure:
            action = "up"
            reason = "burn" if burn >= self.cfg.burn_up else "queue_p99"
        elif idle_held and cooling:
            reason = "cooldown"
        elif idle_held and n <= self.cfg.min_replicas:
            reason = "at_min"
        elif idle_held:
            action, reason = "down", "idle"

        if action == "hold":
            return action

        if action == "up":
            added = self.fabric.scale_up(1)
            self._ups += 1
        else:
            added = self.fabric.scale_down(1)
            self._downs += 1
            self._idle_since = None  # re-arm the idle hold after a drain
        self._last_action_t = now
        if self._last_dir is not None and self._last_dir != action:
            self._flaps += 1
        self._last_dir = action
        obs.emit("autoscale", action=action, reason=reason,
                 replicas_before=n, replicas_after=len(
                     self.fabric.replica_ids()),
                 changed=added, **m)
        return action

    def stats(self) -> dict:
        """Always-present decision tallies (bench's ``extra.autoscale``
        and the trace_diff flap gate read these names)."""
        return {
            "decisions": self._decisions,
            "ups": self._ups,
            "downs": self._downs,
            "flaps": self._flaps,
        }


def main(argv: "list[str] | None" = None) -> int:
    """Module entry: ``--replica`` runs a replica process; the router is
    a library (ServingFabric) driven by the soak/bench/CI harnesses."""
    if argv is None:
        argv = sys.argv[1:]
    if argv and argv[0] == "--replica":
        return replica_main(argv[1:])
    print("usage: fabric --replica INDEX_DIR [options]", file=sys.stderr)
    return 2


if __name__ == "__main__":
    sys.exit(main())
