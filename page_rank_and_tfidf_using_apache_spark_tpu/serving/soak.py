"""Production soak harness: continuous ingest + live traffic + chaos,
scored on SLOs (ISSUE 11; the ROADMAP "production soak" composition).

Everything the repo built separately finally runs AT THE SAME TIME, the
way the "heavy traffic from millions of users" claim implies:

- an **ingest thread** streams a growing synthetic corpus through the
  staged ``chunked_ingest`` pipeline (``run_tfidf_streaming``) and — since
  ISSUE 13 — seals each accumulated delta as an immutable **segment**
  every ``rebuild_every_s`` (serving/segments.py ``seal_segment`` +
  ``commit_append``).  Committed documents are NEVER re-streamed: the
  full-rebuild path (re-ingest the whole accumulated corpus per version)
  is retired, which also fixes the old arrivals-vs-reprocess accounting
  wrinkle at its source — the pipeline now processes each chunk exactly
  once, so arrivals == processed volume by construction;
- the supervisor **hot-swaps** each new manifest generation onto the
  RUNNING server (``TfidfServer.refresh_segments`` — warm first, swap
  under the cache lock, no restart, no request dropped), and a background
  :class:`~..serving.segments.SegmentMerger` compacts small segments under
  the existing retry ladder; ``commit_to_servable_s`` — seal commit →
  first query able to see the segment — is measured per swap and lands in
  the SLO record (seconds, vs a full rebuild);
- **closed-loop clients** drive mixed ``tfidf`` / ``bm25`` / ``prior``
  traffic (the per-request PageRank blend) at a target aggregate QPS;
- a **prior-refresh thread** recomputes PageRank over the document graph
  and hot-swaps the prior operand on the running server
  (``TfidfServer.set_prior`` — no recompile, cache invalidated);
- **deterministic chaos**: any ``GRAFT_CHAOS`` plan stays active
  throughout (transient faults retry invisibly), and at ``loss_at_s`` the
  harness composes in a persistent ``serve_dispatch:lost@1+`` — the
  serving device is gone.  Every batch fails until the supervisor
  *recovers*: it notices client failures, lifts the dead-device plan
  (the replacement chip), rebuilds a warm server from the last committed
  index version, swaps, and probes.  ``time_to_recover_s`` is the
  measured first-failure → first-served-again span.

Scoring is SLOs, not throughput: served p50/p95/p99 over the rolling
window (``obs.metrics`` instruments fed by the ``serve_request`` events
the server already publishes — zero new wiring), availability and
latency **error budgets** with burn rates, recovery time, and the
dropped / double-served invariants.  One ``slo`` record is returned (and
published as an ``slo`` event into the trace, where ``tools/trace_report``
renders it and ``tools/trace_diff`` regresses it round-over-round).  A
live :mod:`obs.export` endpoint serves the same window mid-run — the
record embeds a mid-run endpoint snapshot so the "inspectable while
running" claim is itself tested.

Env knobs (all declared in ``utils/config.GRAFT_ENV_KNOBS``):
``GRAFT_SOAK_DURATION_S``, ``GRAFT_SOAK_QPS``, ``GRAFT_SOAK_SLO_P99_MS``,
``GRAFT_SOAK_SLO_AVAILABILITY``, plus ``GRAFT_METRICS_PORT`` for the
endpoint.  ``bench.py --soak`` is a thin wrapper over :func:`run_soak`.
"""

from __future__ import annotations

import dataclasses
import json
import os
import queue
import shutil
import tempfile
import threading
import time
import urllib.request
from typing import Any, Iterator

import numpy as np

from page_rank_and_tfidf_using_apache_spark_tpu import obs, serving
from page_rank_and_tfidf_using_apache_spark_tpu.io.graph import (
    synthetic_powerlaw,
)
from page_rank_and_tfidf_using_apache_spark_tpu.models.pagerank import (
    run_pagerank,
)
from page_rank_and_tfidf_using_apache_spark_tpu.models.tfidf import (
    run_tfidf_streaming,
)
from page_rank_and_tfidf_using_apache_spark_tpu.obs.export import (
    MetricsExporter,
    metrics_port_from_env,
)
from page_rank_and_tfidf_using_apache_spark_tpu.obs.metrics import (
    MetricsHub,
    TelemetrySink,
)
from page_rank_and_tfidf_using_apache_spark_tpu.resilience import chaos
from page_rank_and_tfidf_using_apache_spark_tpu.serving import (
    segments as sgm,
)
from page_rank_and_tfidf_using_apache_spark_tpu.utils.config import (
    TUNABLE_DEFAULTS,
    Bm25Config,
    PageRankConfig,
    TfidfConfig,
    load_tuned_profile,
    tuned_config,
)
from page_rank_and_tfidf_using_apache_spark_tpu.utils.metrics import (
    MetricsRecorder,
    percentile,
)

_VOCAB_WORDS = 20_000


@dataclasses.dataclass(frozen=True)
class SoakConfig:
    """One soak scenario.  The four starred knobs ride env variables so
    the bench child and the ci.sh smoke gate can shape a round without
    code changes; everything else is a library-level parameter."""

    duration_s: float = 60.0  # * GRAFT_SOAK_DURATION_S
    qps: float = 30.0  # * GRAFT_SOAK_QPS — aggregate closed-loop target
    slo_p99_ms: float = 500.0  # * GRAFT_SOAK_SLO_P99_MS
    availability_target: float = 0.999  # * GRAFT_SOAK_SLO_AVAILABILITY
    clients: int = 3
    window_s: float = 60.0  # rolling SLO window
    rebuild_every_s: float = 12.0  # delta-segment seal/commit cadence
    chunk_interval_s: float = 0.5  # corpus arrival pacing
    prior_refresh_every_s: float = 8.0
    losses: int = 1  # injected device losses (>=1 per the acceptance bar)
    loss_at_s: float | None = None  # default duration/3
    request_timeout_s: float = 20.0
    retry_limit: int = 200  # per logical request (zero-dropped pressure)
    grace_s: float = 30.0  # post-deadline window to land in-flight retries
    seed: int = 7
    vocab_bits: int = 12
    docs_per_chunk: int = 24
    tokens_per_doc: int = 40
    chunk_tokens: int = 1 << 12
    bootstrap_chunks: int = 3
    top_k: int = 10
    max_batch: int = TUNABLE_DEFAULTS["max_batch"]
    prior_alpha: float = 0.25
    prior_iters: int = 5
    scoring: str = "coo"  # serving path (byte-equal either way).  The
    # soak's live set is tiny (thousands of docs), where the impacted
    # path's padded bucket floor costs more than the full postings do —
    # its win scales with corpus nnz (12.5x at 1M docs, bench
    # --serve-scale).  "coo" here keeps the soak's p50 comparable across
    # rounds; flip to "impacted" to soak the latency path itself.
    max_live_segments: int = 4  # merge policy: compact beyond this
    merge_interval_s: float = 2.0  # background merger cadence
    metrics_port: int | None = None  # None -> GRAFT_METRICS_PORT else 0

    def __post_init__(self) -> None:
        if self.duration_s <= 0 or self.qps <= 0 or self.clients < 1:
            raise ValueError("duration_s, qps and clients must be positive")
        if not 0.0 < self.availability_target < 1.0:
            raise ValueError("availability_target must be in (0, 1)")
        if self.losses < 0 or self.slo_p99_ms <= 0:
            raise ValueError("losses must be >= 0 and slo_p99_ms > 0")

    @classmethod
    def from_env(cls, **overrides: Any) -> "SoakConfig":
        """Bench/CI entry: the starred knobs from the environment, the
        rest defaulted (or overridden by the caller)."""
        env: dict[str, Any] = {}
        raw = os.environ.get("GRAFT_SOAK_DURATION_S")
        if raw:
            env["duration_s"] = float(raw)
        raw = os.environ.get("GRAFT_SOAK_QPS")
        if raw:
            env["qps"] = float(raw)
        raw = os.environ.get("GRAFT_SOAK_SLO_P99_MS")
        if raw:
            env["slo_p99_ms"] = float(raw)
        raw = os.environ.get("GRAFT_SOAK_SLO_AVAILABILITY")
        if raw:
            env["availability_target"] = float(raw)
        env.update(overrides)
        return cls(**env)


def _doc_chunks(cfg: SoakConfig) -> Iterator[list[str]]:
    """Endless deterministic Zipf corpus stream, bench-shaped (documents
    over a shared power-law vocabulary so rebuilt indexes stay
    queryable by the clients' Zipf query generator)."""
    rng = np.random.default_rng(cfg.seed)
    while True:
        docs = []
        for _ in range(cfg.docs_per_chunk):
            n = max(int(rng.poisson(cfg.tokens_per_doc)), 4)
            ids = rng.zipf(1.3, n) % _VOCAB_WORDS
            docs.append(" ".join(f"w{i}" for i in ids))
        yield docs


def _prior_ranks(n_docs: int, seed: int, iters: int) -> np.ndarray:
    """The refreshable PageRank prior: ranks over a synthetic document
    citation graph at the current corpus size, normalized to mean 1 so
    the blend scale stays comparable across refreshes."""
    n = max(int(n_docs), 2)
    g = synthetic_powerlaw(n, min(6 * n, n * (n - 1)), seed=seed)
    res = run_pagerank(
        g,
        PageRankConfig(iterations=iters, dangling="redistribute",
                       init="uniform", spmv_impl="segment"),
    )
    out = np.zeros(n, np.float32)
    out[np.asarray(g.node_ids)] = np.asarray(res.ranks, np.float32)
    mean = float(out.mean())
    return out / mean if mean > 0 else out


def _ms(v: float | None) -> float | None:
    return None if v is None else round(v * 1e3, 3)


class _Soak:
    """One soak run's mutable state.  The supervisor owns the calling
    thread; ingest / prior-refresh / client workers are daemon threads.
    Every cross-thread mutation happens under ``self._lock`` (the
    ``unsynced-thread-state`` audit surface); the server reference swap
    is a single atomic rebind readers pick up on their next request."""

    def __init__(self, cfg: SoakConfig, index_dir: str):
        self.cfg = cfg
        self.index_dir = index_dir
        self._lock = threading.Lock()
        self._stop = threading.Event()  # ingest + prior threads
        self._client_stop = threading.Event()
        self._failures: queue.Queue = queue.Queue()
        self._server: serving.TfidfServer | None = None
        self._chaos_ctx: chaos.inject | None = None
        self._outage = False
        self._outage_t0: float | None = None
        self._outage_first_fail: float | None = None
        self._recoveries: list[dict] = []
        self._unexpected: list[float] = []
        self._rebuilds = 0  # delta-segment seal commits (key kept from
        # the full-rebuild era so rounds stay diffable)
        self._prior_refreshes = 0
        self._client_results: dict[int, list[dict]] = {}
        self._mid: dict | None = None
        self._mid_error: dict | None = None
        self._chunks_arrived = 0
        self._tokens_arrived = 0
        self._losses_fired = 0
        self._t0 = 0.0
        self._deadline = 0.0
        # ---- delta-segment state (ISSUE 13) ----
        self._docs_total = 0  # doc_base of the NEXT sealed segment
        self._served_version = 0  # manifest generation the server serves
        self._commit_times: dict[int, float] = {}  # version -> commit t
        self._swaps: list[dict] = []  # per-refresh commit_to_servable_s
        self._build_intervals: list[tuple[float, float]] = []  # seal spans
        self._merger: sgm.SegmentMerger | None = None
        self.hub = MetricsHub(
            window_s=cfg.window_s,
            latency_slo_s=cfg.slo_p99_ms / 1e3,
            availability_target=cfg.availability_target,
        )

    def _stream_cfg(self) -> TfidfConfig:
        """THE ingest config: bootstrap and every rebuild must build
        under one identical config (one config hash) or the server would
        refuse — or worse, silently change semantics — mid-soak."""
        cfg = self.cfg
        # prefetch/pipeline_depth resolve through the knob ladder (tuned
        # profile for this backend, else TUNABLE_DEFAULTS) — not re-stated
        # here; pack_target stays pinned to the soak's chunk size (resume
        # discipline: packed chunk indices must be stable across rebuilds)
        return tuned_config(
            TfidfConfig, load_tuned_profile(),
            vocab_bits=cfg.vocab_bits, chunk_tokens=cfg.chunk_tokens,
            pack_target_tokens=cfg.chunk_tokens,
        )

    def _take_chunk(self, gen: Iterator[list[str]]) -> list[str]:
        """Pull one arriving doc chunk.  With delta segments each chunk
        is streamed exactly once, so arrivals equal processed volume —
        the counter stays as the record's ingest source of truth."""
        docs = next(gen)
        with self._lock:
            self._chunks_arrived += 1
            self._tokens_arrived += sum(len(d.split()) for d in docs)
        return docs

    # ------------------------------------------------------------ serving

    def _seal_delta(self, delta: list[list[str]], scfg: TfidfConfig) -> int | None:
        """Seal the accumulated delta docs as one immutable segment and
        commit it live (the ingest→servable path: seconds, no rebuild).
        Returns the committed manifest version, or None for an empty
        delta.  The seal's wall span is recorded for the ingest-vs-serve
        contention read-out."""
        t0 = time.perf_counter()
        with obs.span("soak.seal", chunks=len(delta)):
            out = run_tfidf_streaming(iter(delta), scfg,
                                      metrics=MetricsRecorder())
            if out.n_docs < 1:
                return None
            with self._lock:
                base = self._docs_total
            # a neutral mean-1 prior placeholder; the prior-refresh
            # thread hot-swaps a real global PageRank blend on cadence
            ref = sgm.seal_segment(
                self.index_dir, out, scfg, doc_base=base,
                ranks=np.ones(out.n_docs, np.float32), bm25=Bm25Config(),
            )
            version = sgm.commit_append(self.index_dir, ref,
                                        scfg.config_hash())
            # the doc-id range is claimed only once the commit landed: a
            # failed seal/commit retries the SAME base, so the global id
            # space can never gap (a gap would wedge the merger's
            # contiguity check and shift every later prior slice)
            with self._lock:
                self._docs_total = base + out.n_docs
        now = time.perf_counter()
        with self._lock:
            self._rebuilds += 1
            self._commit_times[version] = now
            self._build_intervals.append((t0, now))
        obs.emit("soak_seal", version=version, segment=ref.name,
                 doc_base=base, n_docs=out.n_docs)
        return version

    def _build_server(self) -> serving.TfidfServer:
        """Load the committed segment set and stand up a fully-warmed
        replacement (compiles happen HERE, before any flip — the live
        server keeps serving).  Used at bootstrap and for device-loss
        recovery; routine commits ride refresh_segments instead."""
        segset = serving.load_segment_set(self.index_dir)
        scfg = serving.ServeConfig(
            top_k=self.cfg.top_k,
            max_batch=self.cfg.max_batch,
            queue_depth=max(64, 4 * self.cfg.max_batch),
            prior_alpha=self.cfg.prior_alpha,
            scoring=self.cfg.scoring,
        )
        srv = serving.TfidfServer(segset, scfg).start()
        with self._lock:
            self._served_version = segset.version
        return srv

    def _swap_server(self, reason: str) -> None:
        new = self._build_server()
        with self._lock:
            old, self._server = self._server, new
        obs.emit("soak_swap", reason=reason,
                 version=new.index.version, n_docs=new.index.n_docs)
        if old is not None:
            # leftover queued requests fail on stop; their clients retry
            # against the already-live replacement — served, not dropped
            old.stop()

    def _maybe_refresh(self) -> None:
        """Hot-swap the live server onto a newer committed manifest
        generation (a seal commit or a background merge) WITHOUT restart,
        measuring commit→servable per swap."""
        ver = sgm.manifest_version(self.index_dir)
        srv = self._server
        if ver is None or srv is None:
            return
        with self._lock:
            if ver == self._served_version:
                return
        segset = serving.load_segment_set(self.index_dir)
        srv.refresh_segments(segset)
        now = time.perf_counter()
        with self._lock:
            t_commit = self._commit_times.pop(segset.version, None)
            # generations the swap skipped past (burst of commits) are
            # served by this refresh too — drop their stale timestamps
            for v in [v for v in self._commit_times if v < segset.version]:
                self._commit_times.pop(v, None)
            self._served_version = segset.version
            self._swaps.append({
                "version": segset.version,
                "segments": len(segset.segments),
                # merges carry no recorded commit time (the merger owns
                # its own commit); seal commits measure end to end
                "commit_to_servable_s": (
                    round(now - t_commit, 3) if t_commit is not None
                    else None
                ),
            })
        obs.emit("soak_refresh", version=segset.version,
                 segments=len(segset.segments), n_docs=segset.n_docs)

    # ------------------------------------------------------------- chaos

    def _fire_loss(self, now_s: float) -> None:
        env_spec = os.environ.get("GRAFT_CHAOS") or ""
        spec = ";".join(
            s for s in (env_spec, "serve_dispatch:lost@1+") if s
        )
        ctx = chaos.inject(spec)
        ctx.__enter__()
        with self._lock:
            self._chaos_ctx = ctx
            self._outage = True
            self._outage_t0 = time.perf_counter()
            self._outage_first_fail = None
            self._losses_fired += 1
        obs.emit("soak_loss_injected", at_s=round(now_s, 3),
                 loss=self._losses_fired)

    def _recover(self, reason: str, anchor: float) -> None:
        """Replace the lost device: lift the dead-device chaos plan (the
        replacement chip), rebuild a warm server from the last committed
        index version, swap, and probe until a request is served again.
        The measured span is anchored at the FIRST observed failure —
        detection latency is part of the SLO, not an excuse."""
        with obs.span("soak.recover", reason=reason):
            with self._lock:
                ctx, self._chaos_ctx = self._chaos_ctx, None
            if ctx is not None:
                ctx.__exit__(None, None, None)
            self._swap_server(reason=f"recover:{reason}")
            srv = self._server
            assert srv is not None
            srv.query(["soak", "recovery", "probe"],
                      timeout=self.cfg.request_timeout_s)
        t_rec = time.perf_counter() - anchor
        with self._lock:
            self._outage = False
            self._recoveries.append({
                "at_s": round(time.perf_counter() - self._t0, 3),
                "reason": reason,
                "time_to_recover_s": round(t_rec, 3),
            })
        obs.emit("soak_recovered", reason=reason,
                 time_to_recover_s=round(t_rec, 3))
        # stale failure notifications from the outage window are handled
        while True:
            try:
                self._failures.get_nowait()
            except queue.Empty:
                break

    # ------------------------------------------------------------ threads

    def _ingest_loop(self, gen: Iterator[list[str]]) -> None:
        """Stream arrivals and seal each accumulated DELTA as a segment
        on cadence.  Nothing is ever re-streamed: ``pending`` holds only
        chunks that arrived since the last seal — the retired full-rebuild
        path re-ingested the whole accumulated corpus every version, which
        is also why its chunk accounting needed an arrivals-vs-reprocess
        split; here processed == arrived by construction."""
        cfg = self.cfg
        scfg = self._stream_cfg()
        pending: list[list[str]] = []
        next_seal = self._t0 + cfg.rebuild_every_s
        while not self._stop.is_set():
            pending.append(self._take_chunk(gen))
            if time.perf_counter() >= next_seal and pending:
                delta, pending = pending, []
                try:
                    self._seal_delta(delta, scfg)
                except Exception as exc:  # noqa: BLE001 — a failed seal
                    # must not kill ingest: the delta rejoins the queue
                    # and the next tick retries it
                    pending = delta + pending
                    obs.emit("soak_seal_failed",
                             error=f"{type(exc).__name__}: {exc}"[:160])
                next_seal = time.perf_counter() + cfg.rebuild_every_s
            else:
                self._stop.wait(cfg.chunk_interval_s)

    def _prior_loop(self) -> None:
        cfg = self.cfg
        k = 0
        while not self._stop.wait(cfg.prior_refresh_every_s):
            srv = self._server
            if srv is None:
                continue
            try:
                n = srv.index.n_docs
                ranks = _prior_ranks(n, cfg.seed + 1000 + k, cfg.prior_iters)
                srv.set_prior(ranks)
                with self._lock:
                    self._prior_refreshes += 1
                obs.emit("soak_prior_refresh", n_docs=n, refresh=k)
            except Exception as exc:  # noqa: BLE001 — the server may have
                # been swapped/stopped under us; the next tick hits the
                # replacement
                obs.emit("soak_prior_refresh_skipped",
                         error=f"{type(exc).__name__}: {exc}"[:160])
            k += 1

    def _client_loop(self, idx: int) -> None:
        cfg = self.cfg
        rng = np.random.default_rng(cfg.seed * 997 + idx)
        interval = cfg.clients / cfg.qps
        next_t = time.perf_counter() + float(rng.uniform(0, interval))
        # registered up front and appended in place: a client still blocked
        # in fut.result() past the join timeout must not silently drop its
        # completed requests from the dropped/double-served audit
        results: list[dict] = []
        with self._lock:
            self._client_results[idx] = results
        while not self._client_stop.is_set():
            now = time.perf_counter()
            if now < next_t:
                self._client_stop.wait(min(next_t - now, 0.05))
                continue
            next_t = max(next_t + interval, now)  # no burst after stalls
            r = float(rng.random())
            ranker = "tfidf" if r < 0.5 else ("bm25" if r < 0.8 else "prior")
            terms = [f"w{int(rng.zipf(1.3)) % _VOCAB_WORDS}"
                     for _ in range(int(rng.integers(2, 5)))]
            rec: dict = {"ranker": ranker, "attempts": 0, "ok": False,
                         "abandoned": []}
            t_begin = time.perf_counter()
            hard_deadline = self._deadline + cfg.grace_s
            while True:
                rec["attempts"] += 1
                fut = None
                try:
                    srv = self._server
                    if srv is None:
                        raise RuntimeError("no live server")
                    fut = srv.submit(terms, ranker=ranker)
                    fut.result(cfg.request_timeout_s)
                    rec["ok"] = True
                    break
                except Exception as exc:  # noqa: BLE001 — every failure
                    # class retries: outage, swap race, queue drain
                    if fut is not None and not fut.done:
                        # timed out but still in flight: if the old server
                        # later resolves it AND the retry also lands, that
                        # is a double-serve — measured at merge time
                        rec["abandoned"].append(fut)
                    self._failures.put((time.perf_counter(), exc))
                    if (rec["attempts"] >= cfg.retry_limit
                            or time.perf_counter() >= hard_deadline):
                        break
                    time.sleep(0.15)
            rec["e2e_s"] = time.perf_counter() - t_begin
            # absolute span for the ingest-vs-serve contention read-out
            # (_score buckets requests by overlap with seal-build spans)
            rec["t_begin"] = t_begin
            rec["t_end"] = time.perf_counter()
            results.append(rec)

    # --------------------------------------------------------- supervisor

    def _maybe_mid_snapshot(self, exporter: MetricsExporter,
                            now_s: float) -> None:
        if self._mid is not None or self._outage:
            return
        if now_s < self.cfg.duration_s / 2:
            return
        direct = self.hub.snapshot()
        if (direct["latency_s"]["window"]["p99"] is None
                and now_s < 0.8 * self.cfg.duration_s):
            return  # no traffic in the window yet; try again shortly
        try:
            with urllib.request.urlopen(
                exporter.url + "/snapshot.json", timeout=5
            ) as resp:
                http = json.loads(resp.read())
        except Exception as exc:  # noqa: BLE001 — endpoint death must not
            # kill the soak; remember the failure but keep RETRYING on
            # later ticks (a single timed-out fetch must not latch as the
            # round's mid snapshot while the endpoint is healthy)
            self._mid_error = {"at_s": round(now_s, 3),
                               "error": f"{type(exc).__name__}: {exc}"[:160]}
            return
        # The HTTP snapshot was computed BETWEEN two direct reads of the
        # same hub.  The rolling window can legitimately move inside that
        # bracket — a retried request (one deterministic ~50–75 ms
        # backoff) completing mid-fetch, or a ring slot expiring — and at
        # window counts where p99 is effectively the max, one such sample
        # flips the quantile.  Comparing the HTTP read against only the
        # PRE-fetch direct read then manufactures a phantom disagreement
        # between two different moments of one instrument.  Bracket it:
        # re-read after the fetch; while the window is still swinging (and
        # there is run left) retry on a later tick, else record the
        # bracket endpoint closest in time-content to the HTTP read.
        direct2 = self.hub.snapshot()
        d1 = direct["latency_s"]["window"]["p99"]
        d2 = direct2["latency_s"]["window"]["p99"]
        hp = http["latency_s"]["window"]["p99"]
        if (d1 is not None and d2 is not None and d1 != d2
                and now_s < 0.8 * self.cfg.duration_s
                and abs(d1 - d2) > 0.2 * max(d1, d2)):
            return  # window moved mid-measurement; try again shortly
        dbest = d1
        if hp is not None and d2 is not None and (
            d1 is None or abs(d2 - hp) <= abs(d1 - hp)
        ):
            dbest = d2
        self._mid = {
            "at_s": round(now_s, 3),
            "http_p99_ms": _ms(hp),
            "direct_p99_ms": _ms(dbest),
            "window_count": http["latency_s"]["window"]["count"],
        }

    def run(self) -> dict:
        cfg = self.cfg
        sink = TelemetrySink(self.hub)
        obs.bus().attach(sink)
        port = (cfg.metrics_port if cfg.metrics_port is not None
                else (metrics_port_from_env() or 0))
        exporter = MetricsExporter(self.hub, port=port).start()
        gen = _doc_chunks(cfg)
        try:
            # ---- bootstrap: first sealed segment + first warm server ----
            with obs.span("soak.bootstrap"):
                boot = [self._take_chunk(gen)
                        for _ in range(cfg.bootstrap_chunks)]
                scfg = self._stream_cfg()
                self._seal_delta(boot, scfg)
                self._server = self._build_server()
                ranks = _prior_ranks(self._server.index.n_docs, cfg.seed,
                                     cfg.prior_iters)
                self._server.set_prior(ranks)
            self._merger = sgm.SegmentMerger(
                self.index_dir, scfg,
                max_segments=cfg.max_live_segments,
                interval_s=cfg.merge_interval_s,
            ).start()
            self._t0 = time.perf_counter()
            self._deadline = self._t0 + cfg.duration_s
            obs.emit("soak_start", duration_s=cfg.duration_s, qps=cfg.qps,
                     clients=cfg.clients, port=exporter.port)

            loss_times = []
            if cfg.losses > 0:
                first = (cfg.loss_at_s if cfg.loss_at_s is not None
                         else cfg.duration_s / 3.0)
                first = min(first, 0.6 * cfg.duration_s)
                spacing = max(
                    (0.6 * cfg.duration_s - first) / max(cfg.losses - 1, 1),
                    5.0,
                )
                loss_times = [first + i * spacing for i in range(cfg.losses)]

            threads = [
                threading.Thread(target=self._ingest_loop,
                                 args=(gen,), name="soak-ingest",
                                 daemon=True),
                threading.Thread(target=self._prior_loop,
                                 name="soak-prior", daemon=True),
            ] + [
                threading.Thread(target=self._client_loop, args=(i,),
                                 name=f"soak-client-{i}", daemon=True)
                for i in range(cfg.clients)
            ]
            for t in threads:
                t.start()
            clients = threads[2:]

            # ---- the supervisor loop (runs through the grace window so
            # a loss injected late still recovers before scoring) ----
            while True:
                now = time.perf_counter()
                now_s = now - self._t0
                if now >= self._deadline:
                    self._client_stop.set()
                    if all(not c.is_alive() for c in clients):
                        break
                    if now >= self._deadline + cfg.grace_s + 5.0:
                        break  # clients wedged past grace: score what we have
                if loss_times and now_s >= loss_times[0] and not self._outage:
                    loss_times.pop(0)
                    self._fire_loss(now_s)
                try:
                    t_fail, _exc = self._failures.get(timeout=0.05)
                except queue.Empty:
                    t_fail = None
                if t_fail is not None:
                    if self._outage:
                        if self._outage_first_fail is None:
                            self._outage_first_fail = t_fail
                        # the loss has bitten: recover (detection latency
                        # included in the measured span)
                        self._recover("device_loss",
                                      anchor=self._outage_first_fail)
                    else:
                        self._unexpected.append(t_fail)
                        recent = [t for t in self._unexpected
                                  if now - t < 5.0]
                        self._unexpected = recent
                        if len(recent) >= 3:
                            self._unexpected = []
                            self._recover("unexpected", anchor=recent[0])
                if not self._outage:
                    # a newer committed manifest (seal or merge) hot-swaps
                    # onto the RUNNING server — no rebuild, no restart
                    try:
                        self._maybe_refresh()
                    except Exception as exc:  # noqa: BLE001 — a failed
                        # refresh leaves the previous set serving; the
                        # next supervisor tick retries the load/swap
                        obs.emit("soak_refresh_failed",
                                 error=f"{type(exc).__name__}: {exc}"[:160])
                self._maybe_mid_snapshot(exporter, now_s)

            actual_s = time.perf_counter() - self._t0
            self._stop.set()
            threads[0].join(timeout=60.0)
            threads[1].join(timeout=30.0)
            for c in clients:
                c.join(timeout=5.0)
            time.sleep(0.3)  # let abandoned futures settle before auditing
            return self._score(actual_s, exporter)
        finally:
            self._stop.set()
            self._client_stop.set()
            if self._merger is not None:
                self._merger.stop()
            with self._lock:
                ctx, self._chaos_ctx = self._chaos_ctx, None
            if ctx is not None:
                ctx.__exit__(None, None, None)
            srv, self._server = self._server, None
            if srv is not None:
                srv.stop()
            exporter.stop()
            obs.bus().detach(sink)

    # ------------------------------------------------------------- scoring

    def _score(self, actual_s: float, exporter: MetricsExporter) -> dict:
        import jax

        with self._lock:
            per_client = dict(self._client_results)
            recoveries = list(self._recoveries)
            rebuilds = self._rebuilds
            prior_refreshes = self._prior_refreshes
            losses_fired = self._losses_fired
            chunks_arrived = self._chunks_arrived
            tokens_arrived = self._tokens_arrived
            mid = self._mid or self._mid_error
            swaps = list(self._swaps)
            build_ivs = list(self._build_intervals)
            served_version = self._served_version
        recs = [r for results in per_client.values() for r in results]
        dropped = 0
        double_served = 0
        mixed: dict[str, int] = {"tfidf": 0, "bm25": 0, "prior": 0}
        e2e_ok: list[float] = []
        attempts = 0
        for r in recs:
            attempts += r["attempts"]
            mixed[r["ranker"]] += 1
            served = int(r["ok"]) + sum(
                1 for f in r["abandoned"] if f.done and f.error is None
            )
            if served == 0:
                dropped += 1
            double_served += max(served - 1, 0)
            if r["ok"]:
                e2e_ok.append(r["e2e_s"])
        e2e_ok.sort()

        # ---- ingest-vs-serve contention (the PR-11 remaining note, now
        # measured): client e2e latency bucketed by whether the request
        # overlapped a seal-build span — the "before" of this read-out is
        # the full-rebuild era's whole-corpus re-stream per version; the
        # delta seals shrink both the spans and the work inside them ----
        during: list[float] = []
        idle: list[float] = []
        for r in recs:
            if not r["ok"] or "t_begin" not in r:
                continue
            overlapped = any(r["t_begin"] < b and r["t_end"] > a
                             for a, b in build_ivs)
            (during if overlapped else idle).append(r["e2e_s"])
        during.sort()
        idle.sort()
        contention = {
            "during_ingest_requests": len(during),
            "during_ingest_p99_ms": _ms(percentile(during, 0.99)),
            "idle_requests": len(idle),
            "idle_p99_ms": _ms(percentile(idle, 0.99)),
            "ingest_busy_frac": round(
                sum(b - a for a, b in build_ivs) / max(actual_s, 1e-9), 4
            ),
        }
        c2s = [s["commit_to_servable_s"] for s in swaps
               if s.get("commit_to_servable_s") is not None]

        snap = self.hub.snapshot()
        win = snap["latency_s"]["window"]
        tot = snap["latency_s"]["total"]
        counters = snap["counters"]

        def _ctr(name: str) -> int:
            return int(counters.get(name, {}).get("total", 0))

        version = 0
        latest = serving_latest_version(self.index_dir)
        if latest is not None:
            version = latest
        record = {
            "duration_s": round(actual_s, 3),
            "requests": len(recs),
            "attempts": attempts,
            "qps": round(len(e2e_ok) / actual_s, 3) if actual_s > 0 else 0.0,
            "served_p50_ms": _ms(win["p50"]),
            "served_p95_ms": _ms(win["p95"]),
            "served_p99_ms": _ms(win["p99"]),
            "served_p99_cumulative_ms": (
                _ms(tot["p99"]) if tot["count"] else None
            ),
            "client_e2e_p99_ms": _ms(percentile(e2e_ok, 0.99)),
            "error_budget": snap["budgets"],
            "errors": _ctr("serve.errors"),
            "recovery": {
                "losses_injected": losses_fired,
                "recoveries": recoveries,
                "time_to_recover_s": (
                    max(r["time_to_recover_s"] for r in recoveries)
                    if recoveries else None
                ),
            },
            "dropped": dropped,
            "double_served": double_served,
            "ingest": {
                # arrivals == processed volume now: the delta-segment path
                # streams each chunk exactly ONCE (the full-rebuild era
                # re-streamed the accumulated corpus per version, which is
                # why this used to need an arrivals-vs-reprocess split)
                "chunks": chunks_arrived,
                "tokens": tokens_arrived,
                "mode": "segments",
                "rebuilds": rebuilds,  # delta-segment seal commits
                "merges": self._merger.merges if self._merger else 0,
                "live_segments": (
                    len(m.segments)
                    if (m := sgm.latest_manifest(self.index_dir)) else 0
                ),
                "prior_refreshes": prior_refreshes,
                "index_version": version,
                "served_version": served_version,
                # seal commit -> segment servable on the RUNNING server,
                # per hot-swap (the acceptance bar: seconds, not rebuild)
                "commit_to_servable_s": {
                    "max": max(c2s) if c2s else None,
                    "mean": (round(sum(c2s) / len(c2s), 3)
                             if c2s else None),
                    "swaps": len(swaps),
                },
            },
            "contention": contention,
            "chaos_injections": _ctr("chaos.injections"),
            "chaos_losses": _ctr("chaos.losses"),
            "mixed_traffic": mixed,
            "slo_targets": {
                "p99_ms": self.cfg.slo_p99_ms,
                "availability": self.cfg.availability_target,
                "window_s": self.cfg.window_s,
            },
            "endpoint": {"port": exporter.port, "mid": mid},
            "backend": jax.default_backend(),
        }
        obs.emit("slo", **record)
        return record


def serving_latest_version(index_dir: str) -> int | None:
    """Version number behind the LATEST pointer — the manifest generation
    for a segmented directory, the array-dir version for a plain one
    (None when nothing has committed yet)."""
    from page_rank_and_tfidf_using_apache_spark_tpu.utils import (
        checkpoint as ckpt,
    )

    ver = sgm.manifest_version(index_dir)
    if ver is not None:
        return ver
    path = ckpt.latest_array_dir(index_dir)
    if path is None:
        return None
    return int(os.path.basename(path).lstrip("v"))


# ==========================================================================
# fleet soak (ISSUE 17): the same SLO-scored scenario run against the
# multi-PROCESS serving fabric instead of one in-process server
# ==========================================================================


@dataclasses.dataclass(frozen=True)
class FleetSoakConfig:
    """One fleet-soak scenario: N replica processes behind the consistent-
    hash router, continuous delta-segment ingest (each replica hot-swaps
    via its own manifest poll loop), closed-loop clients through
    ``ServingFabric.query`` (sibling re-dispatch under the same request
    id), one replica SIGKILL mid-run, and one rolling restart under a
    committed generation floor — scored on the SAME SLO record shape as
    :func:`run_soak` so ``tools/trace_report`` / ``tools/trace_diff``
    work unchanged."""

    duration_s: float = 45.0  # * GRAFT_SOAK_DURATION_S
    qps: float = 12.0  # * GRAFT_SOAK_QPS — aggregate closed-loop target
    replicas: int = 2  # * GRAFT_FABRIC_REPLICAS
    slo_p99_ms: float = 2000.0  # * GRAFT_SOAK_SLO_P99_MS — cross-process
    # hop + retry ladder: looser than the in-process soak by design
    availability_target: float = 0.99  # * GRAFT_SOAK_SLO_AVAILABILITY
    clients: int = 2
    window_s: float = 120.0  # rolling SLO window
    rebuild_every_s: float = 10.0  # delta-segment seal/commit cadence
    chunk_interval_s: float = 0.5
    kill_at_s: float | None = None  # replica-0 SIGKILL; default duration/3
    roll_at_s: float | None = None  # rolling restart; default 2·duration/3
    request_timeout_s: float = 30.0  # client-side budget per logical query
    grace_s: float = 20.0
    seed: int = 11
    vocab_bits: int = 12
    docs_per_chunk: int = 24
    tokens_per_doc: int = 40
    chunk_tokens: int = 1 << 12
    bootstrap_chunks: int = 3
    top_k: int = 10
    scoring: str = "coo"
    retry_limit: int = 120  # router re-dispatch budget per request
    retry_pause_s: float = 0.25
    # --- stepped-load autoscale scenario (ISSUE 19) -------------------
    # With ``autoscale=True`` the fleet starts at ONE replica and an
    # :class:`~.fabric.Autoscaler` (reading only the federated hub) owns
    # fleet size: clients stay quiet until ``step_at_s``, hammer at full
    # qps until ``idle_at_s``, then go quiet again — the burst's real
    # latencies burn the (deliberately tight) fleet latency budget and
    # scale 1→``replicas``; the idle tail drains the metrics window and
    # scales back down.  SIGKILL/rolling-restart default OFF here: the
    # scale events ARE the membership chaos being audited.
    autoscale: bool = False
    step_at_s: float | None = None  # burst start; default duration/4
    idle_at_s: float | None = None  # burst end; default 0.55 * duration
    cooldown_s: float = 4.0  # * GRAFT_AUTOSCALE_COOLDOWN_S via from_env
    fleet_window_s: float = 10.0  # fleet + replica metrics window —
    # short on purpose so the idle tail's rate/burn decay fits the soak
    autoscale_latency_slo_ms: float = 0.1  # fleet latency budget bound:
    # tighter than any real cross-process serve, so burst traffic burns
    # it hard and the scaler sees genuine measured pressure

    def __post_init__(self) -> None:
        if self.duration_s <= 0 or self.qps <= 0 or self.clients < 1:
            raise ValueError("duration_s, qps and clients must be positive")
        if self.replicas < 1:
            raise ValueError("replicas must be >= 1")
        if self.autoscale and self.replicas < 2:
            raise ValueError("autoscale soak needs replicas >= 2 "
                             "(the scale-up target)")
        if not 0.0 < self.availability_target < 1.0:
            raise ValueError("availability_target must be in (0, 1)")

    @classmethod
    def from_env(cls, **overrides: Any) -> "FleetSoakConfig":
        env: dict[str, Any] = {}
        raw = os.environ.get("GRAFT_SOAK_DURATION_S")
        if raw:
            env["duration_s"] = float(raw)
        raw = os.environ.get("GRAFT_SOAK_QPS")
        if raw:
            env["qps"] = float(raw)
        raw = os.environ.get("GRAFT_FABRIC_REPLICAS")
        if raw:
            env["replicas"] = int(raw)
        raw = os.environ.get("GRAFT_SOAK_SLO_P99_MS")
        if raw:
            env["slo_p99_ms"] = float(raw)
        raw = os.environ.get("GRAFT_SOAK_SLO_AVAILABILITY")
        if raw:
            env["availability_target"] = float(raw)
        raw = os.environ.get("GRAFT_AUTOSCALE_COOLDOWN_S")
        if raw:
            env["cooldown_s"] = float(raw)
        env.update(overrides)
        return cls(**env)


class _FleetSoak:
    """One fleet-soak run.  The supervisor owns the calling thread and
    fires the chaos timeline (SIGKILL, rolling restart); the ingest and
    client workers are daemon threads.  Cross-thread counters live under
    ``self._lock``; the fabric's own state is behind its own lock."""

    def __init__(self, cfg: FleetSoakConfig, index_dir: str):
        from page_rank_and_tfidf_using_apache_spark_tpu.serving import (
            fabric as fab,
        )

        self.cfg = cfg
        self.index_dir = index_dir
        self._fab_mod = fab
        self._lock = threading.Lock()
        self._stop = threading.Event()
        self._client_stop = threading.Event()
        self.fabric: fab.ServingFabric | None = None
        self._client_results: dict[int, list[dict]] = {}
        self._chunks_arrived = 0
        self._tokens_arrived = 0
        self._seals = 0
        self._docs_total = 0
        self._t0 = 0.0
        # router-side delivery ledger, snapshotted by run() right before
        # fabric.stop() tears the fleet down
        self._last_audit: dict | None = None
        # stepped-load gate: clients only send while set (always set in
        # the classic scenario; run() steps it in autoscale mode)
        self._load_on = threading.Event()
        if not cfg.autoscale:
            self._load_on.set()
        self._scaler_stats: dict | None = None
        self._fleet_final: dict | None = None
        # sharded-cache tallies summed over the final /status sweep,
        # snapshotted with the audit before stop() tears the fleet down
        self._cache_final: dict | None = None
        self.hub = MetricsHub(
            window_s=cfg.window_s,
            latency_slo_s=cfg.slo_p99_ms / 1e3,
            availability_target=cfg.availability_target,
        )

    def _fleet_stream_cfg(self) -> TfidfConfig:
        cfg = self.cfg
        return tuned_config(
            TfidfConfig, load_tuned_profile(),
            vocab_bits=cfg.vocab_bits, chunk_tokens=cfg.chunk_tokens,
            pack_target_tokens=cfg.chunk_tokens,
        )

    def _fleet_seal_delta(self, delta: list[list[str]],
                    scfg: TfidfConfig) -> int | None:
        """Seal the accumulated delta as one immutable segment and commit
        it.  Nobody swaps here: every REPLICA notices the new manifest
        generation on its own poll loop and hot-swaps independently —
        that decoupling is the point of the fabric."""
        out = run_tfidf_streaming(iter(delta), scfg,
                                  metrics=MetricsRecorder())
        if out.n_docs < 1:
            return None
        with self._lock:
            base = self._docs_total
        ref = sgm.seal_segment(
            self.index_dir, out, scfg, doc_base=base,
            ranks=np.ones(out.n_docs, np.float32), bm25=Bm25Config(),
        )
        version = sgm.commit_append(self.index_dir, ref,
                                    scfg.config_hash())
        with self._lock:
            self._docs_total = base + out.n_docs
            self._seals += 1
        obs.emit("fleet_seal", version=version, segment=ref.name,
                 doc_base=base, n_docs=out.n_docs)
        return version

    def _fleet_ingest_loop(self, gen: Iterator[list[str]]) -> None:
        cfg = self.cfg
        scfg = self._fleet_stream_cfg()
        pending: list[list[str]] = []
        next_seal = time.perf_counter() + cfg.rebuild_every_s
        while not self._stop.is_set():
            docs = next(gen)
            with self._lock:
                self._chunks_arrived += 1
                self._tokens_arrived += sum(len(d.split()) for d in docs)
            pending.append(docs)
            if time.perf_counter() >= next_seal and pending:
                delta, pending = pending, []
                try:
                    self._fleet_seal_delta(delta, scfg)
                except Exception as exc:  # noqa: BLE001 — the delta
                    # rejoins the queue; the next tick retries it
                    pending = delta + pending
                    obs.emit("fleet_seal_failed",
                             error=f"{type(exc).__name__}: {exc}"[:160])
                next_seal = time.perf_counter() + cfg.rebuild_every_s
            else:
                self._stop.wait(cfg.chunk_interval_s)

    def _fleet_client_loop(self, idx: int) -> None:
        cfg = self.cfg
        rng = np.random.default_rng(cfg.seed * 1013 + idx)
        interval = cfg.clients / cfg.qps
        next_t = time.perf_counter() + float(rng.uniform(0, interval))
        results: list[dict] = []
        with self._lock:
            self._client_results[idx] = results
        while not self._client_stop.is_set():
            if not self._load_on.is_set():
                # stepped load: idle phase — and re-arm the pacing clock
                # so the burst starts at full qps, not with a backlog of
                # catch-up sends
                self._client_stop.wait(0.05)
                next_t = time.perf_counter() + float(
                    rng.uniform(0, interval))
                continue
            now = time.perf_counter()
            if now < next_t:
                self._client_stop.wait(min(next_t - now, 0.05))
                continue
            next_t = max(next_t + interval, now)
            ranker = "tfidf" if rng.random() < 0.7 else "bm25"
            terms = [f"w{int(rng.zipf(1.3)) % _VOCAB_WORDS}"
                     for _ in range(int(rng.integers(2, 5)))]
            rec: dict = {"ranker": ranker, "ok": False}
            t_begin = time.perf_counter()
            err: str | None = None
            try:
                fabric = self.fabric
                if fabric is None:
                    raise RuntimeError("no fabric")
                # the fabric retries internally: sibling re-dispatch
                # under the SAME request id, so a replica dying mid-query
                # is invisible here (or a typed FabricExhausted)
                fabric.query(terms, ranker=ranker,
                             timeout=cfg.request_timeout_s)
                rec["ok"] = True
            except Exception as exc:  # noqa: BLE001 — exhausted/refused
                err = f"{type(exc).__name__}: {exc}"[:160]
            rec["e2e_s"] = time.perf_counter() - t_begin
            # the ROUTER process is where fleet latency is observed:
            # feed the hub through the same serve_request event the
            # in-process server publishes (TelemetrySink contract)
            obs.emit("serve_request", total_s=rec["e2e_s"], error=err)
            results.append(rec)

    def run(self) -> dict:
        cfg = self.cfg
        sink = TelemetrySink(self.hub)
        obs.bus().attach(sink)
        gen = _doc_chunks(cfg)
        fab = self._fab_mod
        recoveries: list[dict] = []
        kills = 0
        roll: dict | None = None
        scaler: fab.Autoscaler | None = None
        try:
            with obs.span("fleet.bootstrap"):
                boot = [next(gen) for _ in range(cfg.bootstrap_chunks)]
                with self._lock:
                    self._chunks_arrived += cfg.bootstrap_chunks
                    self._tokens_arrived += sum(
                        len(d.split()) for c in boot for d in c
                    )
                self._fleet_seal_delta(boot, self._fleet_stream_cfg())
                fabric_cfg = fab.FabricConfig(
                    replicas=1 if cfg.autoscale else cfg.replicas,
                    top_k=cfg.top_k,
                    scoring=cfg.scoring,
                    retry_limit=cfg.retry_limit,
                    retry_pause_s=cfg.retry_pause_s,
                    grace_s=cfg.grace_s,
                )
                if cfg.autoscale:
                    # the scaler reads ONLY the federated hub, so the
                    # fleet must carry real budgets: a tight latency SLO
                    # the burst will burn, the scenario's availability
                    # target, and a short window the idle tail can drain
                    fabric_cfg = dataclasses.replace(
                        fabric_cfg,
                        fleet_window_s=cfg.fleet_window_s,
                        latency_slo_s=cfg.autoscale_latency_slo_ms / 1e3,
                        availability_target=cfg.availability_target,
                    )
                self.fabric = fab.ServingFabric(
                    self.index_dir, fabric_cfg,
                ).start()
            if cfg.autoscale:
                scaler = fab.Autoscaler(self.fabric, fab.AutoscaleConfig(
                    min_replicas=1, max_replicas=cfg.replicas,
                    cooldown_s=cfg.cooldown_s, period_s=0.5,
                    idle_rate_down=0.5, idle_hold_s=2.0,
                )).start()
            self._t0 = time.perf_counter()
            deadline = self._t0 + cfg.duration_s
            # autoscale mode: scale events are the membership chaos; the
            # SIGKILL/rolling-restart timeline stays opt-in via explicit
            # kill_at_s / roll_at_s
            kill_at = (cfg.kill_at_s if cfg.kill_at_s is not None
                       else None if cfg.autoscale else cfg.duration_s / 3.0)
            roll_at = (cfg.roll_at_s if cfg.roll_at_s is not None
                       else None if cfg.autoscale
                       else 2.0 * cfg.duration_s / 3.0)
            step_at = ((cfg.step_at_s if cfg.step_at_s is not None
                        else cfg.duration_s / 4.0)
                       if cfg.autoscale else None)
            idle_at = ((cfg.idle_at_s if cfg.idle_at_s is not None
                        else 0.55 * cfg.duration_s)
                       if cfg.autoscale else None)
            obs.emit("fleet_soak_start", duration_s=cfg.duration_s,
                     qps=cfg.qps, replicas=cfg.replicas,
                     clients=cfg.clients)
            threads = [
                threading.Thread(target=self._fleet_ingest_loop, args=(gen,),
                                 name="fleet-ingest", daemon=True),
            ] + [
                threading.Thread(target=self._fleet_client_loop, args=(i,),
                                 name=f"fleet-client-{i}", daemon=True)
                for i in range(cfg.clients)
            ]
            for t in threads:
                t.start()
            clients = threads[1:]

            killed_pid: int | None = None
            t_kill: float | None = None
            victim = 0
            while time.perf_counter() < deadline:
                now_s = time.perf_counter() - self._t0
                if step_at is not None and now_s >= step_at:
                    step_at = None
                    self._load_on.set()
                    obs.emit("fleet_step", phase="burst",
                             at_s=round(now_s, 3))
                if idle_at is not None and now_s >= idle_at:
                    idle_at = None
                    self._load_on.clear()
                    obs.emit("fleet_step", phase="idle",
                             at_s=round(now_s, 3))
                if kill_at is not None and now_s >= kill_at:
                    kill_at = None
                    killed_pid = self.fabric.kill_replica(victim)
                    t_kill = time.perf_counter()
                    kills += 1
                if t_kill is not None:
                    # recovery = SIGKILL → the victim's REPLACEMENT is
                    # ready (detection latency included, as in run_soak)
                    s = self.fabric.statuses()[victim]
                    if (s is not None and s.get("ready")
                            and s.get("pid") != killed_pid):
                        recoveries.append({
                            "at_s": round(now_s, 3),
                            "reason": "proc_kill",
                            "time_to_recover_s": round(
                                time.perf_counter() - t_kill, 3),
                        })
                        t_kill = None
                if roll_at is not None and now_s >= roll_at:
                    roll_at = None
                    t_roll = time.perf_counter()
                    try:
                        # blocks in THIS thread; clients keep hammering
                        # the fleet from theirs throughout the roll
                        self.fabric.rolling_restart(timeout=60.0)
                        roll = {"ok": True, "roll_s": round(
                            time.perf_counter() - t_roll, 3)}
                    except Exception as exc:  # noqa: BLE001 — a failed
                        # roll is a scored outcome, not a crashed soak
                        roll = {"ok": False,
                                "error": f"{type(exc).__name__}: {exc}"[:160]}
                time.sleep(0.1)

            actual_s = time.perf_counter() - self._t0
            self._client_stop.set()
            for c in clients:
                c.join(timeout=cfg.request_timeout_s + cfg.grace_s)
            self._stop.set()
            threads[0].join(timeout=60.0)
            # snapshot the ledger (and the scaler's tallies + the fleet
            # board) BEFORE stop() tears the fleet down
            if scaler is not None:
                scaler.stop()
                self._scaler_stats = scaler.stats()
                if self.fabric.fleet is not None:
                    fs = self.fabric.fleet.snapshot()["fleet"]
                    self._fleet_final = {
                        "replicas": len(fs["replicas"]),
                        "stale": len(fs["stale"]),
                        "scrapes": fs["scrapes"],
                        "scrape_errors": fs["scrape_errors"],
                    }
            self._last_audit = self.fabric.audit()
            sts = [s for s in self.fabric.statuses() if s is not None]
            if sts:
                hits = sum(int(s.get("peer_hits") or 0) for s in sts)
                misses = sum(int(s.get("peer_misses") or 0) for s in sts)
                tos = sum(int(s.get("peek_timeouts") or 0) for s in sts)
                attempts = hits + misses + tos
                self._cache_final = {
                    "peer_hits": hits,
                    "peer_misses": misses,
                    "peek_timeouts": tos,
                    "peer_hit_rate": (round(hits / attempts, 4)
                                      if attempts else None),
                    "fills": sum(int(s.get("fills") or 0) for s in sts),
                    "peer_stores": sum(int(s.get("peer_stores") or 0)
                                       for s in sts),
                    "breakers_open": sum(int(s.get("breaker_open") or 0)
                                         for s in sts),
                }
            return self._score(actual_s, recoveries, kills, roll)
        finally:
            if scaler is not None:
                scaler.stop()
            self._stop.set()
            self._client_stop.set()
            fabric, self.fabric = self.fabric, None
            if fabric is not None:
                fabric.stop()
            obs.bus().detach(sink)

    def _score(self, actual_s: float, recoveries: list[dict],
               kills: int, roll: dict | None) -> dict:
        import jax

        from page_rank_and_tfidf_using_apache_spark_tpu.serving import (
            fabric as fab,
        )

        with self._lock:
            per_client = dict(self._client_results)
            chunks_arrived = self._chunks_arrived
            tokens_arrived = self._tokens_arrived
            seals = self._seals
        recs = [r for results in per_client.values() for r in results]
        e2e_ok = sorted(r["e2e_s"] for r in recs if r["ok"])
        mixed: dict[str, int] = {"tfidf": 0, "bm25": 0, "prior": 0}
        for r in recs:
            mixed[r["ranker"]] += 1
        # the cross-PROCESS delivery audit: the router's request-id
        # ledger (a replica that died mid-query and its sibling retry
        # share one rid; replicas replay, never re-execute)
        audit = self._last_audit or {}
        snap = self.hub.snapshot()
        win = snap["latency_s"]["window"]
        counters = snap["counters"]
        record = {
            "duration_s": round(actual_s, 3),
            "requests": len(recs),
            "attempts": int(audit.get("requests", 0)
                            + audit.get("retries", 0)),
            "qps": round(len(e2e_ok) / actual_s, 3) if actual_s > 0 else 0.0,
            "served_p50_ms": _ms(win["p50"]),
            "served_p95_ms": _ms(win["p95"]),
            "served_p99_ms": _ms(win["p99"]),
            "client_e2e_p99_ms": _ms(percentile(e2e_ok, 0.99)),
            "error_budget": snap["budgets"],
            "errors": int(counters.get("serve.errors", {})
                          .get("total", 0)),
            "recovery": {
                "losses_injected": kills,
                "recoveries": recoveries,
                "time_to_recover_s": (
                    max(r["time_to_recover_s"] for r in recoveries)
                    if recoveries else None
                ),
            },
            "dropped": int(audit.get("dropped", 0)),
            "double_served": int(audit.get("double_served", 0)),
            "ingest": {
                "chunks": chunks_arrived,
                "tokens": tokens_arrived,
                "mode": "fleet-segments",
                "rebuilds": seals,
                "index_version": serving_latest_version(self.index_dir),
            },
            "fleet": {
                "replicas": self.cfg.replicas,
                "respawns": int(audit.get("respawns", 0)),
                "rolled": int(audit.get("rolled", 0)),
                "roll": roll,
                "floor": fab.read_floor(self.index_dir),
                "retries": int(audit.get("retries", 0)),
                # the handoff's zero-downtime claim, scored: retries the
                # router attributed to a drain window (0 = no client
                # ever saw a roll)
                "roll_retries": int(audit.get("roll_retries", 0)),
            },
            # sharded result cache (ISSUE 20): cross-replica hit rate
            # and breaker state over the run — None when the fleet never
            # exchanged a peek (single replica, or peer_cache off)
            "cache": self._cache_final,
            # autoscale scenario read-outs (None in the classic fleet
            # soak): the scaler's decision tallies, the router audit's
            # membership-change counts, and the final fleet board
            "autoscale": (
                None if self._scaler_stats is None else {
                    **self._scaler_stats,
                    "scale_ups": int(audit.get("scale_ups", 0)),
                    "scale_downs": int(audit.get("scale_downs", 0)),
                    "federation": self._fleet_final,
                }
            ),
            "mixed_traffic": mixed,
            "slo_targets": {
                "p99_ms": self.cfg.slo_p99_ms,
                "availability": self.cfg.availability_target,
                "window_s": self.cfg.window_s,
            },
            "backend": jax.default_backend(),
        }
        obs.emit("slo", **record)
        return record


def run_fleet_soak(cfg: FleetSoakConfig | None = None, *,
                   index_dir: str | None = None) -> dict:
    """Run one FLEET soak scenario — N replica processes behind the
    consistent-hash router, one SIGKILL and one rolling restart under
    load — and return its SLO record (also published as an ``slo`` event
    into any active trace, with a ``fleet`` sub-dict carrying the
    respawn/roll/floor read-outs)."""
    cfg = cfg or FleetSoakConfig.from_env()
    tmp = None
    if index_dir is None:
        tmp = tempfile.mkdtemp(prefix="fleet_idx_")
        index_dir = tmp
    try:
        with obs.span("fleet.run", duration_s=cfg.duration_s):
            return _FleetSoak(cfg, index_dir).run()
    finally:
        if tmp is not None:
            shutil.rmtree(tmp, ignore_errors=True)


def run_soak(cfg: SoakConfig | None = None, *,
             index_dir: str | None = None) -> dict:
    """Run one production-soak scenario and return its SLO record (also
    published as an ``slo`` event into any active trace).  ``index_dir``
    keeps the committed index versions when given; by default they live
    in a temp directory deleted afterwards."""
    cfg = cfg or SoakConfig.from_env()
    tmp = None
    if index_dir is None:
        tmp = tempfile.mkdtemp(prefix="soak_idx_")
        index_dir = tmp
    try:
        with obs.span("soak.run", duration_s=cfg.duration_s):
            return _Soak(cfg, index_dir).run()
    finally:
        if tmp is not None:
            shutil.rmtree(tmp, ignore_errors=True)
