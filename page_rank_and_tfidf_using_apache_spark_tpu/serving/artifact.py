"""Servable index artifact: build once, serve many (ISSUE 8).

Reference counterpart: the Spark job's ``saveAsTextFile`` output that a
downstream service would re-parse.  Here the build side (batch or
streaming TF-IDF, optionally a PageRank run) is serialized ONCE into a
versioned, mmap-loadable index directory, and the serving side
(:mod:`serving.server`) starts by mapping it — no corpus re-ingest, no
tokenizer warmup, no decompression.

Format (``utils/checkpoint.save_array_dir`` — the checkpoint machinery's
array-directory flavor)::

    index_dir/
      LATEST            -> "v0003"          (atomic pointer)
      v0003/
        META.json        {step: 3, config_hash, extra: {...}}
        doc.npy term.npy weight.npy         postings COO, (term, doc)-sorted
        idf.npy df.npy                      dense per-term tables
        ranks.npy                           optional PageRank doc prior

``extra`` carries everything the query side needs to hash queries the same
way the build side hashed documents (the full TfidfConfig JSON), plus
corpus stats (n_docs, nnz, vocab_bits).  ``config_hash`` guards semantic
drift exactly like checkpoints do: a server refuses an index written under
a different TF-IDF semantic configuration.
"""

from __future__ import annotations

import dataclasses
import json

import numpy as np

from page_rank_and_tfidf_using_apache_spark_tpu import obs
from page_rank_and_tfidf_using_apache_spark_tpu.models.tfidf import TfidfOutput
from page_rank_and_tfidf_using_apache_spark_tpu.utils import checkpoint as ckpt
from page_rank_and_tfidf_using_apache_spark_tpu.utils.config import (
    Bm25Config,
    TfidfConfig,
    config_to_json,
)

INDEX_FORMAT = 1  # bump on any layout/meaning change of the arrays below


@dataclasses.dataclass(frozen=True)
class ServableIndex:
    """A loaded (mmap-backed by default) index version, ready for a server
    to device_put.  Arrays are read-only views into the artifact files."""

    path: str
    version: int
    n_docs: int
    vocab_bits: int
    cfg: TfidfConfig
    doc: np.ndarray  # int32 [nnz]
    term: np.ndarray  # int32 [nnz]
    weight: np.ndarray  # f[nnz]
    idf: np.ndarray  # f[vocab]
    df: np.ndarray  # f[vocab]
    ranks: np.ndarray | None  # f[n_docs] PageRank prior, or None
    extra: dict
    # BM25 weights over the SAME postings rows (dataflow/bm25.py) — the
    # A/B-able second ranker; None on indexes built without it.
    bm25_weight: np.ndarray | None = None
    # CSC-by-term postings offsets (ISSUE 13): ``term_offsets[t] ..
    # term_offsets[t+1]`` is term t's posting run in the (term, doc)-sorted
    # COO above — the host-side slice table of the impacted-list scorer.
    # Always present after load (computed for pre-offsets artifacts).
    term_offsets: np.ndarray | None = None  # int64 [vocab + 1]
    # Raw per-pair counts + per-doc lengths (save_index(..., counts=True),
    # the delta-segment layout): what a reader needs to RE-weight this
    # segment's postings under index-wide statistics (serving/segments.py).
    count: np.ndarray | None = None  # f[nnz]
    doc_lengths: np.ndarray | None = None  # int32 [n_docs]

    @property
    def nnz(self) -> int:
        return int(self.doc.shape[0])

    @property
    def vocab_size(self) -> int:
        return 1 << self.vocab_bits


def build_term_offsets(term: np.ndarray, vocab: int) -> np.ndarray:
    """CSC-by-term offsets over a term-sorted postings COO: offsets[t] ..
    offsets[t+1] is term t's posting run.  int64 so a web-scale nnz can
    never wrap; the impacted-list planner slices with it host-side."""
    offsets = np.zeros(vocab + 1, np.int64)
    if term.shape[0]:
        offsets[1:] = np.cumsum(np.bincount(term, minlength=vocab))
    return offsets


def _term_sorted(doc: np.ndarray, term: np.ndarray) -> bool:
    if term.shape[0] < 2:
        return True
    t0, t1 = term[:-1], term[1:]
    return bool(np.all((t1 > t0) | ((t1 == t0) & (doc[1:] >= doc[:-1]))))


def save_index(
    directory: str,
    output: TfidfOutput,
    cfg: TfidfConfig,
    *,
    ranks: np.ndarray | None = None,
    bm25: Bm25Config | None = None,
    counts: bool = False,
    extra: dict | None = None,
) -> str:
    """Serialize a TF-IDF build (+ optional PageRank doc prior and BM25
    second-ranker weights) as the next index version under ``directory``;
    returns the version path.

    ``ranks`` must be per-*document* priors aligned with the output's doc
    ids (how documents map onto graph nodes is the caller's contract —
    the PageRank-over-citation-graph correspondence of the reference).
    ``bm25`` re-weights the SAME postings COO from the output's raw
    counts (dataflow/bm25.py) into one extra array, making the artifact
    servable under either ranker per request.

    The postings are stored strictly (term, doc)-sorted with a CSC-by-term
    ``term_offsets`` table (ISSUE 13): the batch pipeline already emits
    that order, the streaming pipeline's chunk-major concatenation is
    re-sorted here ONCE at build time so the serving side can slice a
    term's whole posting run by offset — the impacted-list layout.
    ``counts=True`` additionally persists the raw per-pair counts and
    per-doc lengths, which is what makes a *delta segment* self-contained:
    a reader can re-weight this slice of the corpus under index-wide
    DF/N statistics (serving/segments.py) without ever re-ingesting it.
    """
    if ranks is not None and ranks.shape[0] != output.n_docs:
        raise ValueError(
            f"ranks prior has {ranks.shape[0]} entries but the index holds "
            f"{output.n_docs} documents"
        )
    doc = np.ascontiguousarray(output.doc, np.int32)
    term = np.ascontiguousarray(output.term, np.int32)
    weight = np.ascontiguousarray(output.weight)
    count = (np.ascontiguousarray(output.count)
             if output.count is not None else None)
    perm: np.ndarray | None = None
    if not _term_sorted(doc, term):
        perm = np.lexsort((doc, term))
        doc, term, weight = doc[perm], term[perm], weight[perm]
        if count is not None:
            count = count[perm]
    arrays: dict[str, np.ndarray] = {
        "doc": doc,
        "term": term,
        "weight": weight,
        "idf": np.ascontiguousarray(output.idf),
        "df": np.ascontiguousarray(output.df),
        "term_offsets": build_term_offsets(term, cfg.vocab_size),
    }
    if counts:
        if count is None or output.doc_lengths is None:
            raise ValueError(
                "counts=True needs TfidfOutput.count/doc_lengths — rebuild "
                "with a pipeline version that exports raw counts"
            )
        arrays["count"] = count
        arrays["doc_lengths"] = np.ascontiguousarray(
            output.doc_lengths, np.int32
        )
    if ranks is not None:
        arrays["ranks"] = np.ascontiguousarray(ranks)
    if bm25 is not None:
        from page_rank_and_tfidf_using_apache_spark_tpu.dataflow.bm25 import (
            bm25_from_tfidf,
        )

        bw = np.ascontiguousarray(bm25_from_tfidf(output, bm25))
        arrays["bm25_weight"] = bw if perm is None else bw[perm]
    version = ckpt.next_version(directory)
    meta = {
        "format": INDEX_FORMAT,
        "n_docs": int(output.n_docs),
        "vocab_bits": int(output.vocab_bits),
        "nnz": int(output.nnz),
        "has_ranks": ranks is not None,
        "has_bm25": bm25 is not None,
        "bm25_config": (json.loads(config_to_json(bm25))
                        if bm25 is not None else None),
        "tfidf_config": json.loads(config_to_json(cfg)),
        **(extra or {}),
    }
    with obs.span("serve.index_build", version=version, nnz=output.nnz):
        path = ckpt.save_array_dir(
            directory, version, arrays, cfg.config_hash(), extra=meta
        )
    return path


def load_index(
    directory: str,
    *,
    version: int | None = None,
    mmap: bool = True,
    expect_config_hash: str | None = None,
) -> ServableIndex:
    """Load an index version (LATEST by default) as a :class:`ServableIndex`.

    ``mmap=True`` maps the arrays instead of copying them: server startup
    touches metadata only, and concurrent server processes share one page
    cache for the postings."""
    if version is not None:
        import os

        path = os.path.join(directory, f"v{version:04d}")
    else:
        path = ckpt.latest_array_dir(directory)
        if path is None:
            raise FileNotFoundError(
                f"no committed index version under {directory!r} "
                "(build one with serving.artifact.save_index / "
                "cli.tfidf --save-index)"
            )
    ver, arrays, extra = ckpt.load_array_dir(
        path, expect_config_hash, mmap=mmap
    )
    fmt = int(extra.get("format", 0))
    if fmt != INDEX_FORMAT:
        raise ValueError(
            f"index {path} has format {fmt}; this build reads format "
            f"{INDEX_FORMAT} — rebuild the artifact"
        )
    cfg = TfidfConfig(**extra["tfidf_config"])
    offsets = arrays.get("term_offsets")
    if offsets is None:
        # pre-ISSUE-13 artifact: same COO meaning, no stored offsets —
        # derive them at load when the postings happen to be term-sorted
        # (every batch-built artifact).  A legacy chunk-major streaming
        # artifact keeps offsets None and serves via the COO path only;
        # artifacts THIS build writes are always sorted at save time.
        t = np.asarray(arrays["term"])
        if _term_sorted(np.asarray(arrays["doc"]), t):
            offsets = build_term_offsets(t, 1 << int(extra["vocab_bits"]))
    return ServableIndex(
        path=path,
        version=int(ver),
        n_docs=int(extra["n_docs"]),
        vocab_bits=int(extra["vocab_bits"]),
        cfg=cfg,
        doc=arrays["doc"],
        term=arrays["term"],
        weight=arrays["weight"],
        idf=arrays["idf"],
        df=arrays["df"],
        ranks=arrays.get("ranks"),
        extra=extra,
        bm25_weight=arrays.get("bm25_weight"),
        term_offsets=offsets,
        count=arrays.get("count"),
        doc_lengths=arrays.get("doc_lengths"),
    )
