"""serving/ — the online query path (ISSUE 8).

Everything before this package was batch: build ranks, build TF-IDF, exit.
This package turns ``ops.tfidf.score_query`` into a real serving stack:

- :mod:`serving.artifact` — a versioned, mmap-loadable index (postings +
  IDF/DF tables + optional PageRank prior) written through the checkpoint
  machinery's array-directory format, so a server starts WITHOUT
  re-ingesting the corpus;
- :mod:`serving.server` — a long-lived server that loads the artifact
  once, keeps device-resident postings and compiled batched runners warm,
  drains a bounded request queue into padded micro-batches (the
  ``grow_chunk_cap`` padding policy, so the batch-shape matrix is finite
  and tier-2 proves zero per-request recompiles), fuses top-k on device,
  and fronts it all with a hot-query LRU result cache.

*RankMap* (platform-aware serving of dense decompositions, PAPERS.md) is
the reference shape; DrJAX's one-jaxpr discipline is why the batched query
step is a single registered jit entry point (``analysis/registry.py``:
``tfidf_score_query_batch``) rather than per-request dispatches.
"""

from page_rank_and_tfidf_using_apache_spark_tpu.serving.artifact import (
    ServableIndex,
    load_index,
    save_index,
)
from page_rank_and_tfidf_using_apache_spark_tpu.serving.segments import (
    SegmentMerger,
    SegmentSet,
    commit_append,
    load_segment_set,
    seal_segment,
)
from page_rank_and_tfidf_using_apache_spark_tpu.serving.server import (
    RANKERS,
    ServeConfig,
    ServerShutdown,
    TfidfServer,
    batch_cap,
    impacted_pad_plan,
    serve_pad_plan,
)

__all__ = [
    "RANKERS",
    "FabricConfig",
    "SegmentMerger",
    "SegmentSet",
    "ServableIndex",
    "ServeConfig",
    "ServerShutdown",
    "ServingFabric",
    "SoakConfig",
    "TfidfServer",
    "batch_cap",
    "commit_append",
    "commit_floor",
    "impacted_pad_plan",
    "load_index",
    "load_segment_set",
    "read_floor",
    "run_soak",
    "save_index",
    "seal_segment",
    "serve_pad_plan",
]


def __getattr__(name: str):
    # serving.soak pulls in models/ and io/ (the ingest + PageRank side);
    # serving.fabric pulls in subprocess/HTTP plumbing — both lazy so
    # plain serving users don't pay their import chains.
    if name in ("SoakConfig", "run_soak"):
        from page_rank_and_tfidf_using_apache_spark_tpu.serving import soak

        return getattr(soak, name)
    if name in ("FabricConfig", "ServingFabric", "commit_floor",
                "read_floor"):
        from page_rank_and_tfidf_using_apache_spark_tpu.serving import fabric

        return getattr(fabric, name)
    raise AttributeError(name)
