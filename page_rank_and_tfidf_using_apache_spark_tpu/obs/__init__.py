"""obs/ — unified run telemetry (ISSUE 4).

The Spark-UI/event-log counterpart this reproduction was missing: every
long path publishes structured events onto one process-global bus
(:mod:`obs.events`), host phases open context-propagated spans bridged to
``jax.profiler.TraceAnnotation`` (:mod:`obs.trace`), and a traced run
writes a crash-safe per-event-flushed JSONL trace plus a startup/exit
manifest (:mod:`obs.runtime`, :mod:`obs.manifest`).  ``tools/trace_report.py``
(stdlib-only, importable from the jax-free bench parent) reconstructs
per-phase wall-time breakdowns, retry/chaos tallies per site, the chunk
timeline, and the last incomplete span from a trace file — a SIGKILLed
child yields a full accounting instead of a stderr tail.

Spark-UI correspondence (also in README "Observability"):

==========================  =============================================
Spark                       here
==========================  =============================================
event log                   ``<name>.<pid>.trace.jsonl`` (JSONL sink)
application page / conf     ``<name>.<pid>.manifest.json``
stage/task timeline         spans (``obs.span("tfidf.chunk", chunk=24)``)
stage counters              ``obs.counter/gauge/histogram`` + run summary
task failure / retry log    ``retry``/``backoff``/``watchdog``/``chaos``
                            /``degraded``/``exhausted`` events
==========================  =============================================

Env knobs: ``GRAFT_TRACE_DIR`` (default trace directory — a run started
with no explicit dir writes here; unset = in-memory only) and
``GRAFT_LOG_LEVEL`` (stderr log level, utils/metrics.py).  Both declared
in ``utils/config.GRAFT_ENV_KNOBS``.
"""

from page_rank_and_tfidf_using_apache_spark_tpu.obs.events import (
    Aggregates,
    EventBus,
    JsonlSink,
    MemorySink,
)
from page_rank_and_tfidf_using_apache_spark_tpu.obs.manifest import knob_snapshot
from page_rank_and_tfidf_using_apache_spark_tpu.obs.runtime import (
    Run,
    bus,
    counter,
    current_run,
    emit,
    end_run,
    gauge,
    histogram,
    run,
    span,
    start_run,
    tracer,
)
from page_rank_and_tfidf_using_apache_spark_tpu.obs.trace import SpanTracer

# Live SLO instruments (ISSUE 11): rolling-window histograms / error
# budgets (obs.metrics) and the pull-based HTTP snapshot surface
# (obs.export).  Imported after runtime so their obs-package imports see
# a fully-initialized module.
from page_rank_and_tfidf_using_apache_spark_tpu.obs import export  # noqa: E402
from page_rank_and_tfidf_using_apache_spark_tpu.obs import federation  # noqa: E402
from page_rank_and_tfidf_using_apache_spark_tpu.obs import metrics  # noqa: E402
from page_rank_and_tfidf_using_apache_spark_tpu.obs.federation import (  # noqa: E402
    FleetHub,
)
from page_rank_and_tfidf_using_apache_spark_tpu.obs.metrics import (  # noqa: E402
    ErrorBudget,
    MetricsHub,
    RollingHistogram,
    StreamingHistogram,
    TelemetrySink,
    WindowedCounter,
)

__all__ = [
    "Aggregates",
    "ErrorBudget",
    "EventBus",
    "FleetHub",
    "JsonlSink",
    "MemorySink",
    "MetricsHub",
    "RollingHistogram",
    "Run",
    "SpanTracer",
    "StreamingHistogram",
    "TelemetrySink",
    "WindowedCounter",
    "export",
    "federation",
    "metrics",
    "bus",
    "counter",
    "current_run",
    "emit",
    "end_run",
    "gauge",
    "histogram",
    "knob_snapshot",
    "run",
    "span",
    "start_run",
    "tracer",
]
