"""Context-propagated span tracer.

Reference counterpart: the Spark UI stage/task timeline.  A *span* is one
named host-side phase (``obs.span("tfidf.chunk", chunk=24)``) with a
monotonic start/stop, an id, and a parent — nested spans form the per-run
call tree that ``tools/trace_report.py`` reconstructs into a wall-time
breakdown.

Design points:

- **Context propagation** rides on :mod:`contextvars`: each thread starts
  with an empty span stack, so spans opened on the streaming tokenizer
  thread nest among themselves and never steal the main thread's parent
  (the bug class the ``unsynced-thread-state`` lint patrols).  Explicit
  cross-thread parentage is available via ``span(..., parent=sid)``.
- **Crash evidence by construction**: ``span_begin`` is published (and the
  JSONL sink flushes it) *before* the body runs, so a SIGKILL mid-span
  leaves a begin with no end — exactly what trace_report reports as "the
  last incomplete span".  An exception ends the span with
  ``status="error:<Type>"`` and re-raises.
- **XLA bridge**: when jax is already imported, every span also enters a
  ``jax.profiler.TraceAnnotation`` of the same name, so host phases line
  up with device timelines in a TensorBoard profile.  The bridge never
  *imports* jax (``"jax" in sys.modules`` gates it): a span can never be
  the thing that drags the jax import chain in.  (Truly jax-free
  processes — the bench parent — do not import this package at all; they
  read trace artifacts through the stdlib-only ``tools/trace_report.py``.)
"""

from __future__ import annotations

import contextlib
import contextvars
import sys
import threading
from typing import Any, Iterator

import time

from page_rank_and_tfidf_using_apache_spark_tpu.obs.events import EventBus

_current_span: contextvars.ContextVar[int | None] = contextvars.ContextVar(
    "graft_obs_span", default=None
)


class SpanTracer:
    """Allocates span ids and publishes span_begin/span_end to a bus."""

    def __init__(self, bus: EventBus):
        self._bus = bus
        self._lock = threading.Lock()
        self._next_id = 1

    def _new_id(self) -> int:
        with self._lock:
            sid = self._next_id
            self._next_id += 1
        return sid

    def current(self) -> int | None:
        """Span id of the innermost open span in this context (None at the
        top level — including on a freshly spawned thread)."""
        return _current_span.get()

    @contextlib.contextmanager
    def span(
        self, name: str, /, *, parent: int | None = None, **attrs: Any
    ) -> Iterator[int]:
        sid = self._new_id()
        par = parent if parent is not None else _current_span.get()
        t0 = time.perf_counter()
        self._bus.publish(
            "span_begin", span=sid, parent=par, name=name, attrs=attrs
        )
        token = _current_span.set(sid)
        status = "ok"
        with contextlib.ExitStack() as bridge:
            if "jax" in sys.modules:  # annotate, never import
                try:
                    from jax.profiler import TraceAnnotation

                    bridge.enter_context(TraceAnnotation(name))
                except Exception:  # noqa: BLE001 — the bridge is best-effort
                    pass
            try:
                yield sid
            except BaseException as exc:
                status = f"error:{type(exc).__name__}"
                raise
            finally:
                _current_span.reset(token)
                self._bus.publish(
                    "span_end",
                    span=sid,
                    parent=par,
                    name=name,
                    secs=time.perf_counter() - t0,
                    status=status,
                    attrs=attrs,
                )
