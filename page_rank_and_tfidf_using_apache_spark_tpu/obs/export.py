"""Pull-based live-metrics surface: a tiny stdlib HTTP endpoint over a
:class:`obs.metrics.MetricsHub` (ISSUE 11).

Reference counterpart: the Spark UI's REST endpoint — you point a browser
(or ``tools/slo_watch.py``, or a Prometheus scraper) at a *running*
driver and read its live stage/SLA numbers without touching the run.
Here:

- ``GET /snapshot.json`` — the hub's full JSON snapshot (rolling-window
  latency quantiles, counters/rates, gauges, error budgets);
- ``GET /metrics`` — the same state as Prometheus text exposition;
- ``GET /healthz`` — liveness (``ok``).

The port comes from the ``GRAFT_METRICS_PORT`` env knob (declared in
``utils/config.GRAFT_ENV_KNOBS``): unset/empty means "no exporter" for
the from-env helpers; ``0`` binds an ephemeral port (the soak harness
uses this so parallel runs never collide — the bound port is published in
the ``metrics_export`` event and the SLO record).  The server binds
127.0.0.1 only: this is an operator's inspection hatch, not a public
listener.

Wiring is one call::

    hub = obs.metrics.MetricsHub(window_s=60)
    obs.bus().attach(obs.metrics.TelemetrySink(hub))   # live fold-in
    exporter = obs.export.MetricsExporter(hub, port=9109).start()
    ...
    exporter.stop()

or, for the common "serve the process-default hub when the knob is set"
case, :func:`serve_metrics_from_env`.
"""

from __future__ import annotations

import json
import os
import selectors
import socket
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

from page_rank_and_tfidf_using_apache_spark_tpu.obs import runtime as _rt
from page_rank_and_tfidf_using_apache_spark_tpu.obs.metrics import (
    MetricsHub,
    TelemetrySink,
)


def _make_handler(hub: MetricsHub, *, routes=None, ready=None):
    """The handler class behind :class:`MetricsExporter`.

    ``routes`` extends the surface without forking the endpoint: a dict
    mapping ``(method, path)`` (e.g. ``("POST", "/query")``) to a
    callable ``body_bytes -> (status, content_type, body_str)`` — the
    serving-fabric replica rides its query/status API on the same server
    (and the same ``graft-metrics-http`` thread) as its health checks.
    ``ready`` is an optional zero-arg readiness predicate: when it
    returns False, ``/healthz`` answers 503 — a replica that is still
    warming, or is held below the fleet's committed generation floor,
    reports itself unroutable through the SAME endpoint the router
    health-checks."""
    routes = routes or {}

    class Handler(BaseHTTPRequestHandler):
        server_version = "graft-metrics/1"

        def log_message(self, *args) -> None:  # quiet: stderr is the run's
            pass

        def _send(self, code: int, body: str, ctype: str) -> None:
            data = body.encode("utf-8")
            self.send_response(code)
            self.send_header("Content-Type", ctype)
            self.send_header("Content-Length", str(len(data)))
            self.end_headers()
            self.wfile.write(data)

        def _dispatch(self, method: str, body: bytes) -> None:
            path = self.path.split("?", 1)[0]
            try:
                fn = routes.get((method, path))
                if fn is not None:
                    code, ctype, payload = fn(body)
                    self._send(code, payload, ctype)
                elif method == "GET" and path in ("/snapshot.json",
                                                  "/snapshot", "/json"):
                    self._send(200, json.dumps(hub.snapshot(), default=float),
                               "application/json")
                elif method == "GET" and path == "/metrics":
                    self._send(200, hub.prometheus(),
                               "text/plain; version=0.0.4")
                elif method == "GET" and path in ("/", "/healthz"):
                    if ready is not None and not ready():
                        self._send(503, "unready\n", "text/plain")
                    else:
                        self._send(200, "ok\n", "text/plain")
                else:
                    self._send(404, "not found\n", "text/plain")
            except Exception as exc:  # noqa: BLE001 — never kill the server
                try:
                    self._send(500, f"{type(exc).__name__}: {exc}\n",
                               "text/plain")
                except Exception:  # noqa: BLE001 — client already gone
                    pass

        def do_GET(self) -> None:  # noqa: N802 — BaseHTTPRequestHandler API
            self._dispatch("GET", b"")

        def do_POST(self) -> None:  # noqa: N802 — BaseHTTPRequestHandler API
            n = int(self.headers.get("Content-Length") or 0)
            self._dispatch("POST", self.rfile.read(n) if n else b"")

    return Handler


def reuse_port_supported() -> bool:
    """Whether this platform exposes ``SO_REUSEPORT`` — the kernel-level
    listener-group steering the fabric's drain handoff rides on.  Where
    it is missing (some non-Linux platforms) callers fall back to the
    retry-carried roll."""
    return hasattr(socket, "SO_REUSEPORT")


class _ReusePortHTTPServer(ThreadingHTTPServer):
    """ThreadingHTTPServer that joins an ``SO_REUSEPORT`` listener group
    before binding: a successor process can bind the SAME port while the
    predecessor still serves, and the kernel steers each new connection
    to exactly one of them — the zero-downtime drain-handoff transport
    (serving/fabric.py rolling_restart)."""

    def server_bind(self) -> None:
        if reuse_port_supported():
            self.socket.setsockopt(
                socket.SOL_SOCKET, socket.SO_REUSEPORT, 1)
        super().server_bind()

    def serve_forever(self, poll_interval: float = 0.5) -> None:
        """socketserver's loop checks the shutdown flag BEFORE accepting
        a ready connection, so a handshake already queued in this
        listener's backlog when shutdown() lands is abandoned and RST on
        close — in a reuseport group that is one spurious client reset
        per drain, i.e. one roll-attributed retry.  Reorder to
        accept-then-check and sweep the backlog dry with a zero-timeout
        select before exiting, so the socket closes empty and the drain
        truly hands every steered connection off."""
        self._BaseServer__is_shut_down.clear()  # graftlint: disable=unsynced-thread-state (threading.Event is internally locked; stdlib serve_forever mutates the same pair lock-free)
        try:
            with selectors.SelectSelector() as selector:
                selector.register(self, selectors.EVENT_READ)
                while not self._BaseServer__shutdown_request:
                    if selector.select(poll_interval):
                        self._handle_request_noblock()
                    self.service_actions()
                while selector.select(0):
                    self._handle_request_noblock()
        finally:
            self._BaseServer__shutdown_request = False  # graftlint: disable=unsynced-thread-state (single-writer handshake flag; shutdown() only ever sets it True and blocks on the event below)
            self._BaseServer__is_shut_down.set()  # graftlint: disable=unsynced-thread-state (threading.Event is internally locked)


class MetricsExporter:
    """Background HTTP server publishing one hub's live snapshot.

    ``port=0`` binds an ephemeral port; read :attr:`port` after
    :meth:`start`.  The serve loop runs on a daemon thread
    (``graft-metrics-http``); handler threads mutate nothing — every read
    goes through the hub's own locks (the ``unsynced-thread-state``
    audit surface is the hub, not the exporter)."""

    def __init__(self, hub: MetricsHub, *, port: int = 0,
                 host: str = "127.0.0.1", routes=None, ready=None,
                 reuse_port: bool = False, drain: bool = False):
        self.hub = hub
        self.host = host
        self.port = int(port)
        self.routes = routes
        self.ready = ready
        # reuse_port: bind into an SO_REUSEPORT listener group so a
        # successor can share the port during a drain handoff.  drain:
        # handler threads become non-daemon and stop() blocks until every
        # in-flight request has been answered (ThreadingMixIn's
        # block_on_close join) — the predecessor side of the handoff.
        self.reuse_port = bool(reuse_port)
        self.drain = bool(drain)
        self._srv: ThreadingHTTPServer | None = None
        self._thread: threading.Thread | None = None

    def start(self) -> "MetricsExporter":
        if self._srv is not None:
            return self
        server_cls = (_ReusePortHTTPServer if self.reuse_port
                      else ThreadingHTTPServer)
        self._srv = server_cls(
            (self.host, self.port),
            _make_handler(self.hub, routes=self.routes, ready=self.ready),
        )
        self._srv.daemon_threads = not self.drain
        self.port = int(self._srv.server_address[1])
        self._thread = threading.Thread(
            target=self._srv.serve_forever, name="graft-metrics-http",
            daemon=True,
        )
        self._thread.start()
        _rt.emit("metrics_export", host=self.host, port=self.port)
        return self

    @property
    def url(self) -> str:
        return f"http://{self.host}:{self.port}"

    def stop(self) -> None:
        srv, self._srv = self._srv, None
        if srv is not None:
            srv.shutdown()
            srv.server_close()
        if self._thread is not None:
            self._thread.join(timeout=5.0)
            self._thread = None

    def __enter__(self) -> "MetricsExporter":
        return self.start()

    def __exit__(self, *exc: object) -> None:
        self.stop()


# ------------------------------------------------------- process default hub

_default_lock = threading.Lock()
_default_hub: MetricsHub | None = None
_default_sink: TelemetrySink | None = None


def default_hub() -> MetricsHub:
    """The process's shared hub, lazily created and bus-attached on first
    use — any long-lived entry point (cli.serve, the soak harness) that
    calls :func:`serve_metrics_from_env` starts folding the event stream
    into it with zero publisher changes."""
    global _default_hub, _default_sink
    with _default_lock:
        if _default_hub is None:
            _default_hub = MetricsHub()
            _default_sink = TelemetrySink(_default_hub)
            _rt.bus().attach(_default_sink)
        return _default_hub


def metrics_port_from_env() -> int | None:
    """The GRAFT_METRICS_PORT knob: None = exporter disabled (unset or
    empty), 0 = ephemeral port, else the literal port."""
    raw = os.environ.get("GRAFT_METRICS_PORT")
    if raw is None or raw.strip() == "":
        return None
    return int(raw)


def serve_metrics_from_env(
    hub: MetricsHub | None = None,
) -> MetricsExporter | None:
    """Start an exporter when GRAFT_METRICS_PORT is set; None otherwise.
    With no explicit hub, serves (and implicitly bus-attaches) the
    process-default one."""
    port = metrics_port_from_env()
    if port is None:
        return None
    return MetricsExporter(hub or default_hub(), port=port).start()
