"""Rolling-window SLO instruments: bounded-memory streaming histograms,
windowed counters/rates, and error-budget trackers (ISSUE 11).

Reference counterpart: the *live* Spark UI — stage/SLA numbers you can
read while the job runs — where the batch-era ``tools/trace_report.py``
only reconstructs them after death.  Everything here is:

- **O(bins), not O(events)** — a soak that serves requests for hours must
  not grow its telemetry with traffic.  :class:`StreamingHistogram` keeps
  one fixed geometric bin array (count/sum/min/max stay exact; quantiles
  are correct to within one bin, i.e. a relative error bounded by
  ``growth - 1``), and :class:`RollingHistogram` keeps ``slots`` such
  arrays in a time ring so p50/p95/p99 can be read *over the last window*
  at any moment.
- **Thread-safe** — observations arrive from the serve drain thread, the
  ingest pipeline's workers and the exporter's HTTP threads concurrently.
- **Fed by the bus, not by call sites** — :class:`TelemetrySink` attaches
  to the existing ``obs.EventBus`` and folds the events every long path
  already publishes (``serve_request``, ``metric``, ``retry``, ``chaos``,
  ...) into a :class:`MetricsHub`.  No publisher changed to make the
  telemetry live.

The pull side lives in :mod:`obs.export` (HTTP snapshot endpoint) and
``tools/slo_watch.py`` (terminal renderer); the soak harness
(:mod:`serving.soak`) scores its SLOs from a hub snapshot.

**Exact cross-process merge (ISSUE 19).**  Every instrument shares one
geometric bin layout, so fleet federation is *exact arithmetic*, not
estimation: ``to_mergeable()`` exports the raw state (bin counts, sums,
min/max, window tallies — JSON-safe, inf-free) and ``merge()`` folds a
peer's mergeable in.  Counts, sums and min/max merge byte-exactly;
quantiles read off the merged bins keep the same one-bin tolerance a
single process has (relative error <= ``growth - 1``).  The contract
that makes this sound — every process agreeing on metric names and bin
layout — is declared in ``analysis/registry.METRIC_SCHEMAS`` and
machine-checked by the ``metric-name-drift`` lint; a mismatched layout
raises at merge time rather than silently skewing fleet percentiles.
"""

from __future__ import annotations

import math
import threading
import time
from typing import Any, Callable

import numpy as np


class HistogramBins:
    """Shared geometric binning: ``n_bins`` fixed-width-in-log-space bins
    from ``lo`` to ``hi`` plus an underflow and an overflow slot.  A value
    maps to the bin whose ``[edge_i, edge_{i+1})`` range holds it, so any
    quantile read off the bin counts is within one bin of the exact
    sample quantile — a relative error of at most ``growth - 1``."""

    __slots__ = ("lo", "hi", "growth", "n_bins", "_log_lo", "_inv_log_g")

    def __init__(self, lo: float = 1e-6, hi: float = 1e6,
                 growth: float = 1.1):
        if not (0 < lo < hi) or growth <= 1.0:
            raise ValueError(f"need 0 < lo < hi and growth > 1, got "
                             f"lo={lo} hi={hi} growth={growth}")
        self.lo = float(lo)
        self.hi = float(hi)
        self.growth = float(growth)
        self._log_lo = math.log(lo)
        self._inv_log_g = 1.0 / math.log(growth)
        self.n_bins = int(math.ceil((math.log(hi) - self._log_lo)
                                    * self._inv_log_g))

    @property
    def n_slots(self) -> int:
        """Total count-array length: n_bins + underflow + overflow."""
        return self.n_bins + 2

    def index(self, v: float) -> int:
        if not v > self.lo:  # <= lo, zero, negative, NaN -> underflow
            return 0
        if v >= self.hi:
            return self.n_bins + 1
        i = int((math.log(v) - self._log_lo) * self._inv_log_g)
        return min(max(i, 0), self.n_bins - 1) + 1

    def index_many(self, values: np.ndarray) -> np.ndarray:
        v = np.asarray(values, np.float64)  # graftlint: disable=dtype-drift (host-only telemetry math; never dispatched)
        out = np.zeros(v.shape, np.int64)
        pos = v > self.lo
        with np.errstate(divide="ignore", invalid="ignore"):
            i = ((np.log(np.where(pos, v, 1.0)) - self._log_lo)
                 * self._inv_log_g).astype(np.int64)
        out[pos] = np.clip(i[pos], 0, self.n_bins - 1) + 1
        out[v >= self.hi] = self.n_bins + 1
        return out

    def edge(self, i: int) -> float:
        return self.lo * self.growth ** i

    def value(self, slot: int, vmin: float, vmax: float) -> float:
        """Representative value of one slot (geometric bin midpoint),
        clamped into the exactly-tracked [vmin, vmax] observed range."""
        if slot <= 0:
            return vmin
        if slot >= self.n_bins + 1:
            return vmax
        mid = self.edge(slot - 1) * math.sqrt(self.growth)
        return min(max(mid, vmin), vmax)

    def quantile_from_counts(
        self, counts: np.ndarray, p: float, vmin: float, vmax: float
    ) -> float | None:
        """Nearest-rank quantile over a bin-count array (None when
        empty) — the same rank convention as ``utils.metrics.percentile``,
        resolved to bin granularity."""
        total = int(counts.sum())
        if total <= 0:
            return None
        rank = max(min(-(-int(p * 100) * total // 100), total), 1)
        cum = 0
        for slot, c in enumerate(counts):
            cum += int(c)
            if cum >= rank:
                return self.value(slot, vmin, vmax)
        return vmax


# Default bin layout for latency-flavored instruments: 1 microsecond to
# ~17 minutes at 10% relative resolution (~208 bins).
LATENCY_BINS = dict(lo=1e-6, hi=1e3, growth=1.1)


def _bins_sig(bins: HistogramBins) -> dict[str, float]:
    return {"lo": bins.lo, "hi": bins.hi, "growth": bins.growth}


def _require_same_bins(bins: HistogramBins, sig: dict[str, Any],
                       what: str) -> None:
    """Merge precondition: identical bin layout on both sides.  A layout
    mismatch is a fleet-config bug (metric-name-drift territory), and
    folding counts across different edges would silently corrupt every
    quantile — so it raises instead."""
    theirs = (float(sig["lo"]), float(sig["hi"]), float(sig["growth"]))
    ours = (bins.lo, bins.hi, bins.growth)
    if theirs != ours:
        raise ValueError(
            f"{what}.merge: bin layout mismatch "
            f"(ours lo/hi/growth={ours}, theirs={theirs})"
        )


class StreamingHistogram:
    """Cumulative fixed-bin histogram with online quantiles.

    Memory is O(bins) forever: count/sum/min/max are tracked exactly,
    per-event samples are never retained (the unbounded-memory risk the
    old run-end ``Aggregates`` carried), and quantiles are read from the
    bin counts to within one bin of the exact value."""

    def __init__(self, lo: float = 1e-6, hi: float = 1e6,
                 growth: float = 1.1, *, bins: HistogramBins | None = None):
        self.bins = bins or HistogramBins(lo, hi, growth)
        self._lock = threading.Lock()
        self._counts = np.zeros(self.bins.n_slots, np.int64)
        self._count = 0
        self._sum = 0.0
        self._min = math.inf
        self._max = -math.inf

    def observe(self, value: float) -> None:
        v = float(value)
        with self._lock:
            self._count += 1
            self._sum += v
            if v < self._min:
                self._min = v
            if v > self._max:
                self._max = v
            self._counts[self.bins.index(v)] += 1

    def observe_many(self, values) -> None:
        v = np.asarray(values, np.float64).ravel()  # graftlint: disable=dtype-drift (host-only telemetry math; never dispatched)
        if v.size == 0:
            return
        idx = self.bins.index_many(v)
        add = np.bincount(idx, minlength=self.bins.n_slots)
        with self._lock:
            self._count += int(v.size)
            self._sum += float(v.sum())
            self._min = min(self._min, float(v.min()))
            self._max = max(self._max, float(v.max()))
            self._counts += add

    @property
    def count(self) -> int:
        with self._lock:
            return self._count

    def quantile(self, p: float) -> float | None:
        with self._lock:
            return self.bins.quantile_from_counts(
                self._counts, p, self._min, self._max
            )

    def approx_bytes(self) -> int:
        """Telemetry-state footprint — constant in the event count (the
        10^6-event regression test pins this)."""
        return int(self._counts.nbytes) + 64

    def to_mergeable(self) -> dict[str, Any]:
        """Raw exportable state: bin layout + counts + exact
        count/sum/min/max.  JSON-safe — min/max are None until the first
        observation (never ±inf on the wire)."""
        with self._lock:
            return {
                "kind": "streaming_histogram",
                "bins": _bins_sig(self.bins),
                "counts": self._counts.tolist(),
                "count": self._count,
                "sum": self._sum,
                "min": self._min if self._count else None,
                "max": self._max if self._count else None,
            }

    def merge(self, m: dict[str, Any]) -> None:
        """Fold a peer's :meth:`to_mergeable` in.  Count/sum/min/max and
        the bin counts merge exactly; quantiles of the merged state keep
        the one-bin tolerance.  One-shot: merging the same export twice
        double-counts (the federation layer re-merges *fresh* scrapes
        into a fresh hub instead)."""
        _require_same_bins(self.bins, m["bins"], "StreamingHistogram")
        add = np.asarray(m["counts"], np.int64)
        if add.shape != self._counts.shape:
            raise ValueError(
                f"StreamingHistogram.merge: {add.shape} vs "
                f"{self._counts.shape} slots"
            )
        with self._lock:
            self._counts += add
            self._count += int(m["count"])
            self._sum += float(m["sum"])
            if m.get("min") is not None:
                self._min = min(self._min, float(m["min"]))
            if m.get("max") is not None:
                self._max = max(self._max, float(m["max"]))

    def snapshot(self) -> dict[str, Any]:
        with self._lock:
            counts = self._counts.copy()
            count, total = self._count, self._sum
            vmin, vmax = self._min, self._max
        q = lambda p: self.bins.quantile_from_counts(counts, p, vmin, vmax)  # noqa: E731
        return {
            "count": count,
            "sum": total,
            "min": vmin if count else 0.0,
            "max": vmax if count else 0.0,
            "mean": total / count if count else 0.0,
            "p50": q(0.50) if count else 0.0,
            "p90": q(0.90) if count else 0.0,
            "p95": q(0.95) if count else 0.0,
            "p99": q(0.99) if count else 0.0,
        }


class RollingHistogram:
    """Windowed quantiles over a ring of per-slot bin-count rows.

    The window is ``window_s`` seconds split into ``slots`` equal slots;
    an observation lands in the slot owning its timestamp, and a snapshot
    merges only the slots still inside the window — so ``quantile(0.99)``
    is the p99 *of roughly the last window_s seconds*, readable at any
    moment of an arbitrarily long run.  Memory: O(slots * bins)."""

    def __init__(self, window_s: float = 60.0, slots: int = 30, *,
                 lo: float = 1e-6, hi: float = 1e6, growth: float = 1.1,
                 bins: HistogramBins | None = None,
                 clock: Callable[[], float] = time.monotonic):
        if window_s <= 0 or slots < 1:
            raise ValueError("window_s must be > 0 and slots >= 1")
        self.bins = bins or HistogramBins(lo, hi, growth)
        self.window_s = float(window_s)
        self.slots = int(slots)
        self.slot_s = self.window_s / self.slots
        self._clock = clock
        self._lock = threading.Lock()
        self._rows = np.zeros((self.slots, self.bins.n_slots), np.int64)
        self._row_ids = np.full(self.slots, -1, np.int64)  # absolute slot no
        self._min = math.inf  # lifetime extremes: clamp-only, exactness
        self._max = -math.inf  # lives in the cumulative instruments

    def _row_for(self, slot_no: int) -> np.ndarray:
        i = slot_no % self.slots
        if self._row_ids[i] != slot_no:
            self._rows[i].fill(0)
            self._row_ids[i] = slot_no
        return self._rows[i]

    def observe(self, value: float) -> None:
        v = float(value)
        now = self._clock()
        with self._lock:
            if v < self._min:
                self._min = v
            if v > self._max:
                self._max = v
            self._row_for(int(now / self.slot_s))[self.bins.index(v)] += 1

    def _merged_locked(self) -> np.ndarray:
        cur = int(self._clock() / self.slot_s)
        live = (self._row_ids > cur - self.slots) & (self._row_ids <= cur)
        if not live.any():
            return np.zeros(self.bins.n_slots, np.int64)
        return self._rows[live].sum(axis=0)

    def quantile(self, p: float) -> float | None:
        with self._lock:
            return self.bins.quantile_from_counts(
                self._merged_locked(), p, self._min, self._max
            )

    def window_count(self) -> int:
        with self._lock:
            return int(self._merged_locked().sum())

    def to_mergeable(self) -> dict[str, Any]:
        """Exportable live window: the ring's row ids are keyed to this
        process's own monotonic clock, so raw rows do not transport —
        what crosses the wire is the *merged current window* plus the
        lifetime extremes."""
        with self._lock:
            merged = self._merged_locked()
            vmin, vmax = self._min, self._max
        return {
            "kind": "rolling_histogram",
            "bins": _bins_sig(self.bins),
            "window_s": self.window_s,
            "window_counts": merged.tolist(),
            "min": None if vmin == math.inf else vmin,
            "max": None if vmax == -math.inf else vmax,
        }

    def merge(self, m: dict[str, Any]) -> None:
        """Fold a peer's exported window into the slot owning *now*: the
        peer's last-window traffic lands at merge time, so a window read
        shortly after covers the union of both fleets' recent traffic
        (counts exact, quantiles within one bin).  One-shot — see
        :meth:`StreamingHistogram.merge`."""
        _require_same_bins(self.bins, m["bins"], "RollingHistogram")
        if float(m["window_s"]) != self.window_s:
            raise ValueError(
                f"RollingHistogram.merge: window_s mismatch "
                f"({m['window_s']} vs {self.window_s})"
            )
        add = np.asarray(m["window_counts"], np.int64)
        if add.shape != (self.bins.n_slots,):
            raise ValueError(
                f"RollingHistogram.merge: {add.shape} vs "
                f"({self.bins.n_slots},) slots"
            )
        now = self._clock()
        with self._lock:
            if m.get("min") is not None:
                self._min = min(self._min, float(m["min"]))
            if m.get("max") is not None:
                self._max = max(self._max, float(m["max"]))
            self._row_for(int(now / self.slot_s))[:] += add

    def snapshot(self) -> dict[str, Any]:
        with self._lock:
            merged = self._merged_locked()
            vmin, vmax = self._min, self._max
        count = int(merged.sum())
        q = lambda p: self.bins.quantile_from_counts(merged, p, vmin, vmax)  # noqa: E731
        return {
            "window_s": self.window_s,
            "count": count,
            "p50": q(0.50),
            "p90": q(0.90),
            "p95": q(0.95),
            "p99": q(0.99),
        }


class WindowedCounter:
    """Cumulative total plus a sliding-window sum/rate (ring of per-slot
    sums, O(slots) memory)."""

    def __init__(self, window_s: float = 60.0, slots: int = 30, *,
                 clock: Callable[[], float] = time.monotonic):
        self.window_s = float(window_s)
        self.slots = int(slots)
        self.slot_s = self.window_s / self.slots
        self._clock = clock
        self._lock = threading.Lock()
        self._sums = np.zeros(self.slots, np.float64)  # graftlint: disable=dtype-drift (host-only telemetry state; never dispatched)
        self._slot_ids = np.full(self.slots, -1, np.int64)
        self._total = 0.0
        self._t0: float | None = None

    def add(self, n: float = 1.0) -> None:
        now = self._clock()
        with self._lock:
            if self._t0 is None:
                self._t0 = now
            self._total += n
            slot_no = int(now / self.slot_s)
            i = slot_no % self.slots
            if self._slot_ids[i] != slot_no:
                self._sums[i] = 0.0
                self._slot_ids[i] = slot_no
            self._sums[i] += n

    def total(self) -> float:
        with self._lock:
            return self._total

    def _window_sum_locked(self) -> float:
        cur = int(self._clock() / self.slot_s)
        live = (self._slot_ids > cur - self.slots) & (self._slot_ids <= cur)
        return float(self._sums[live].sum())

    def window_sum(self) -> float:
        with self._lock:
            return self._window_sum_locked()

    def rate(self) -> float:
        """Events/sec over the window actually covered so far (a counter
        younger than the window divides by its age, not the window)."""
        now = self._clock()
        with self._lock:
            if self._t0 is None:
                return 0.0
            covered = max(min(now - self._t0, self.window_s), self.slot_s)
            return self._window_sum_locked() / covered

    def snapshot(self) -> dict[str, Any]:
        return {"total": self.total(), "rate_per_s": round(self.rate(), 4)}

    def to_mergeable(self) -> dict[str, Any]:
        """Exportable state: exact cumulative total, the live window sum,
        and how many seconds of window the counter has actually covered
        (so a merged rate divides by real coverage, not assumed age)."""
        now = self._clock()
        with self._lock:
            covered = (0.0 if self._t0 is None
                       else max(min(now - self._t0, self.window_s),
                                self.slot_s))
            return {
                "kind": "windowed_counter",
                "window_s": self.window_s,
                "total": self._total,
                "window_sum": self._window_sum_locked(),
                "covered_s": covered,
            }

    def merge(self, m: dict[str, Any]) -> None:
        """Fold a peer's export in: totals add exactly; the peer's window
        sum lands in the slot owning *now*; coverage extends ``_t0`` so
        the merged rate is over the widest window either side covered.
        One-shot — see :meth:`StreamingHistogram.merge`."""
        if float(m["window_s"]) != self.window_s:
            raise ValueError(
                f"WindowedCounter.merge: window_s mismatch "
                f"({m['window_s']} vs {self.window_s})"
            )
        now = self._clock()
        with self._lock:
            self._total += float(m["total"])
            covered = float(m.get("covered_s") or 0.0)
            if covered > 0.0:
                t0 = now - covered
                self._t0 = t0 if self._t0 is None else min(self._t0, t0)
            w = float(m["window_sum"])
            if w:
                slot_no = int(now / self.slot_s)
                i = slot_no % self.slots
                if self._slot_ids[i] != slot_no:
                    self._sums[i] = 0.0
                    self._slot_ids[i] = slot_no
                self._sums[i] += w


class ErrorBudget:
    """SLO target + error-budget accounting over a sliding window.

    ``target`` is the good-event fraction the SLO promises (0.999 =
    "99.9% of requests succeed / meet latency").  The budget is the
    allowed bad fraction ``1 - target``; ``consumed_frac`` is how much of
    the *cumulative* budget the run has burned, and ``burn_rate`` is the
    classic SRE multiplier — the windowed bad-fraction divided by the
    allowed fraction (1.0 = burning exactly the budget; 10 = ten times
    too fast)."""

    def __init__(self, target: float, *, window_s: float = 60.0,
                 slots: int = 30,
                 clock: Callable[[], float] = time.monotonic):
        if not 0.0 < target < 1.0:
            raise ValueError(f"target must be in (0, 1), got {target}")
        self.target = float(target)
        self._all = WindowedCounter(window_s, slots, clock=clock)
        self._bad = WindowedCounter(window_s, slots, clock=clock)

    def observe(self, good: bool) -> None:
        self._all.add(1.0)
        if not good:
            self._bad.add(1.0)

    def snapshot(self) -> dict[str, Any]:
        total = self._all.total()
        bad = self._bad.total()
        allowed = (1.0 - self.target) * total
        if allowed > 0:
            consumed = bad / allowed
        else:
            consumed = 0.0 if bad == 0 else 1e9  # no traffic yet, or all-bad
        w_total = self._all.window_sum()
        w_bad = self._bad.window_sum()
        burn = ((w_bad / w_total) / (1.0 - self.target)) if w_total > 0 else 0.0
        return {
            "target": self.target,
            "total": int(total),
            "bad": int(bad),
            "allowed": round(allowed, 3),
            "consumed_frac": round(min(consumed, 1e9), 4),
            "window_bad": int(w_bad),
            "burn_rate": round(min(burn, 1e9), 4),
        }

    def to_mergeable(self) -> dict[str, Any]:
        return {
            "kind": "error_budget",
            "target": self.target,
            "all": self._all.to_mergeable(),
            "bad": self._bad.to_mergeable(),
        }

    def merge(self, m: dict[str, Any]) -> None:
        """Fold a peer's budget in.  Targets must agree — a fleet whose
        replicas promise different SLOs has no single budget to burn
        (and METRIC_SCHEMAS pins the fleet-wide target names)."""
        if float(m["target"]) != self.target:
            raise ValueError(
                f"ErrorBudget.merge: target mismatch "
                f"({m['target']} vs {self.target})"
            )
        self._all.merge(m["all"])
        self._bad.merge(m["bad"])


class MetricsHub:
    """The process's live SLO instrument board.

    Holds the rolling/streaming latency histograms, lazily-created
    windowed counters, gauges, and named error budgets; renders one JSON
    snapshot (:meth:`snapshot`) and one Prometheus-style text page
    (:meth:`prometheus`).  Fed by :class:`TelemetrySink` from the event
    bus — publishers need no new wiring."""

    def __init__(self, *, window_s: float = 60.0, slots: int = 30,
                 latency_slo_s: float | None = None,
                 availability_target: float | None = None,
                 clock: Callable[[], float] = time.monotonic):
        self.window_s = float(window_s)
        self._clock = clock
        self._slots = int(slots)
        bins = HistogramBins(**LATENCY_BINS)
        self.latency = RollingHistogram(window_s, slots, bins=bins,
                                        clock=clock)
        self.latency_total = StreamingHistogram(bins=bins)
        self.queue_wait = RollingHistogram(window_s, slots, bins=bins,
                                           clock=clock)
        self.latency_slo_s = latency_slo_s
        self._lock = threading.Lock()
        self._counters: dict[str, WindowedCounter] = {}
        self._gauges: dict[str, float] = {}
        self.budgets: dict[str, ErrorBudget] = {}
        if availability_target is not None:
            self.budgets["availability"] = ErrorBudget(
                availability_target, window_s=window_s, slots=slots,
                clock=clock)
        if latency_slo_s is not None:
            # p99 target expressed as a budget: 1% of requests may exceed
            # the latency bound before the budget starts burning
            self.budgets["latency"] = ErrorBudget(
                0.99, window_s=window_s, slots=slots, clock=clock)

    # ------------------------------------------------------------- feeding

    def counter(self, name: str) -> WindowedCounter:
        with self._lock:
            c = self._counters.get(name)
            if c is None:
                c = self._counters[name] = WindowedCounter(
                    self.window_s, self._slots, clock=self._clock)
            return c

    def count(self, name: str, n: float = 1.0) -> None:
        self.counter(name).add(n)

    def gauge(self, name: str, value: float) -> None:
        with self._lock:
            self._gauges[name] = float(value)

    def observe_request(self, total_s: float, ok: bool,
                        queue_wait_s: float | None = None) -> None:
        """One served request: latency instruments see only successful
        requests (a failure's latency is time-to-fail, not service time);
        every request feeds the counters and budgets."""
        self.count("serve.requests")
        if ok:
            self.count("serve.ok")
            self.latency.observe(total_s)
            self.latency_total.observe(total_s)
            if queue_wait_s is not None:
                self.queue_wait.observe(queue_wait_s)
        else:
            self.count("serve.errors")
        budget = self.budgets.get("availability")
        if budget is not None:
            budget.observe(ok)
        budget = self.budgets.get("latency")
        if budget is not None:
            budget.observe(ok and total_s <= (self.latency_slo_s or math.inf))

    def ingest_event(self, event: dict[str, Any]) -> None:
        """Fold one bus event into the instruments (TelemetrySink's
        fan-in).  Unknown kinds are ignored — the hub only ever *reads*
        the existing event vocabulary."""
        kind = event.get("kind")
        if kind == "serve_request":
            self.observe_request(
                float(event.get("total_s") or 0.0),
                ok=not event.get("error"),
                queue_wait_s=event.get("queue_wait_s"),
            )
        elif kind == "chaos":
            self.count("chaos.injections")
            fault = event.get("fault")
            if fault in ("lost", "device_lost"):
                self.count("chaos.losses")
        elif kind in ("retry", "backoff", "degraded", "exhausted",
                      "watchdog", "checkpoint_save"):
            self.count(kind)
        elif kind == "metric":
            sub = event.get("event")
            if sub in ("chunk", "super_chunk"):
                self.count("ingest.chunks")
                self.count("ingest.tokens", float(event.get("tokens") or 0))
            elif sub == "ingest_overlap":
                self.gauge("h2d_overlap_frac",
                           float(event.get("h2d_overlap_frac") or 0.0))
        elif kind in ("serve_start", "soak_rebuild", "soak_swap",
                      "soak_loss_injected", "soak_recovered",
                      "soak_prior_refresh"):
            self.count(kind)

    # ------------------------------------------------------- federation

    def to_mergeable(self) -> dict[str, Any]:
        """The hub's full raw state for exact cross-process federation —
        embedded in every :meth:`snapshot` so any process's
        ``/snapshot.json`` is federable with no extra endpoint."""
        with self._lock:
            counters = dict(self._counters)
            gauges = dict(self._gauges)
        budgets = dict(self.budgets)
        return {
            "window_s": self.window_s,
            "latency": self.latency.to_mergeable(),
            "latency_total": self.latency_total.to_mergeable(),
            "queue_wait": self.queue_wait.to_mergeable(),
            "counters": {k: c.to_mergeable()
                         for k, c in sorted(counters.items())},
            "gauges": gauges,
            "budgets": {k: b.to_mergeable()
                        for k, b in sorted(budgets.items())},
        }

    def merge_mergeable(self, m: dict[str, Any]) -> None:
        """Fold one process's :meth:`to_mergeable` export into this hub:
        histograms/counters/budgets merge exactly (missing counters and
        budgets are created on first sight); gauges are last-write-wins —
        the federation layer exports per-replica gauges under replica
        labels instead of pretending point-in-time values add."""
        self.latency.merge(m["latency"])
        self.latency_total.merge(m["latency_total"])
        self.queue_wait.merge(m["queue_wait"])
        for name, cm in m.get("counters", {}).items():
            self.counter(name).merge(cm)
        for name, v in m.get("gauges", {}).items():
            self.gauge(name, v)
        for name, bm in m.get("budgets", {}).items():
            with self._lock:
                b = self.budgets.get(name)
                if b is None:
                    b = self.budgets[name] = ErrorBudget(
                        float(bm["target"]), window_s=self.window_s,
                        slots=self._slots, clock=self._clock)
            b.merge(bm)

    # ------------------------------------------------------------ rendering

    def snapshot(self) -> dict[str, Any]:
        with self._lock:
            counters = dict(self._counters)
            gauges = dict(self._gauges)
        return {
            "at_wall": time.time(),
            "window_s": self.window_s,
            "latency_s": {
                "window": self.latency.snapshot(),
                "total": self.latency_total.snapshot(),
            },
            "queue_wait_s": self.queue_wait.snapshot(),
            "counters": {k: c.snapshot() for k, c in sorted(counters.items())},
            "gauges": gauges,
            "budgets": {k: b.snapshot() for k, b in sorted(self.budgets.items())},
            "mergeable": self.to_mergeable(),
        }

    def prometheus(self) -> str:
        """Prometheus text exposition (0.0.4 flavor) of the same state."""
        def _name(raw: str) -> str:
            return "graft_" + "".join(
                c if (c.isalnum() or c == "_") else "_" for c in raw
            )

        lines: list[str] = []
        snap = self.snapshot()
        win = snap["latency_s"]["window"]
        for q in ("p50", "p90", "p95", "p99"):
            v = win.get(q)
            if v is not None:
                lines.append(
                    f'graft_serve_latency_seconds{{window="rolling",'
                    f'quantile="0.{q[1:]}"}} {v:.6g}'
                )
        tot = snap["latency_s"]["total"]
        lines.append(f"graft_serve_latency_seconds_count {tot['count']}")
        lines.append(f"graft_serve_latency_seconds_sum {tot['sum']:.6g}")
        for name, c in snap["counters"].items():
            lines.append(f"{_name(name)}_total {c['total']:.6g}")
            lines.append(f"{_name(name)}_rate {c['rate_per_s']:.6g}")
        for name, v in snap["gauges"].items():
            lines.append(f"{_name(name)} {v:.6g}")
        for name, b in snap["budgets"].items():
            lines.append(
                f'graft_slo_budget_consumed{{slo="{name}"}} '
                f"{b['consumed_frac']:.6g}"
            )
            lines.append(
                f'graft_slo_burn_rate{{slo="{name}"}} {b["burn_rate"]:.6g}'
            )
        return "\n".join(lines) + "\n"


class TelemetrySink:
    """EventBus sink adapter: attach to ``obs.bus()`` and every event the
    existing publishers emit feeds the hub — the zero-new-call-site-wiring
    contract of the live telemetry layer.  A raising sink would be
    detached by the bus; the hub's folds only touch its own locks."""

    def __init__(self, hub: MetricsHub):
        self.hub = hub

    def emit(self, event: dict[str, Any]) -> None:
        self.hub.ingest_event(event)

    def close(self) -> None:
        pass
