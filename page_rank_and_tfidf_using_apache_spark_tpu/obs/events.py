"""Event model, bus and sinks for the run-telemetry subsystem.

Reference counterpart: the Spark event log (``spark.eventLog.enabled``) —
an append-only record of everything the driver did, written durably enough
that a dead executor still leaves evidence.  Here the equivalents are:

- :class:`EventBus` — the process-global publish point.  Every event is a
  flat dict stamped with a monotonic timestamp (``t``, ``time.perf_counter``
  — comparable across threads within one process), a wall clock (``wall``),
  a per-process sequence number, and the emitting thread.  Publishers never
  block on a broken sink: a sink that raises is detached with one stderr
  warning (telemetry must never kill the run it observes).
- :class:`JsonlSink` — the crash-safe trace file: one JSON line per event,
  appended and flushed *per event*, so a SIGKILLed child still leaves every
  completed event on disk (the BENCH_r05 failure mode: a 420 s timeout kill
  used to leave nothing but a scraped stderr tail).  A kill mid-write can
  truncate only the final line; readers (tools/trace_report.py) skip it.
- :class:`MemorySink` — in-memory capture for tests.
- :class:`Aggregates` — counters / gauges / histograms folded in-process,
  summarized once at run end (the Spark UI stage-counter equivalent).
"""

from __future__ import annotations

import json
import sys
import threading
import time
from typing import Any, Callable


def jsonable(obj: Any) -> Any:
    """``json.dumps`` fallback: numpy scalars → float, everything else →
    repr.  The trace must never lose an event to a serialization error."""
    try:
        return float(obj)
    except (TypeError, ValueError):
        return repr(obj)


class EventBus:
    """Thread-safe fan-out of structured events to attached sinks."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._sinks: list[Any] = []
        self._seq = 0

    def attach(self, sink: Any) -> None:
        with self._lock:
            if sink not in self._sinks:
                self._sinks.append(sink)

    def detach(self, sink: Any) -> None:
        with self._lock:
            if sink in self._sinks:
                self._sinks.remove(sink)

    def sink_count(self) -> int:
        with self._lock:
            return len(self._sinks)

    def publish(self, kind: str, /, **fields: Any) -> dict[str, Any]:
        """Stamp and deliver one event.  Returns the event dict (tests and
        callers may want the assigned ``seq``/``t``).  ``kind`` is
        positional-only so arbitrary field dicts (e.g. forwarded
        MetricsRecorder records) can never collide with it."""
        event: dict[str, Any] = {
            "t": time.perf_counter(),
            "wall": time.time(),
            "kind": kind,
            "thread": threading.current_thread().name,
        }
        for key, value in fields.items():
            # the envelope stamps are load-bearing for trace_report: a
            # colliding payload field is prefixed, never dropped or allowed
            # to overwrite them
            event[key if key not in event and key != "seq" else f"f_{key}"] = value
        with self._lock:
            event["seq"] = self._seq
            self._seq += 1
            sinks = tuple(self._sinks)
        for sink in sinks:
            try:
                sink.emit(event)
            except Exception as exc:  # noqa: BLE001 — observability must not kill the run
                self.detach(sink)
                print(
                    f"obs: detached broken sink {type(sink).__name__}: "
                    f"{type(exc).__name__}: {exc}",
                    file=sys.stderr,
                )
                # Best-effort tombstone: if the failure was transient (one
                # full-disk write, an NFS blip), a final marker line keeps a
                # truncated-but-finished run distinguishable from a SIGKILL
                # in trace_report ("sink_detached" vs no evidence at all).
                try:
                    sink.emit(
                        {
                            "t": time.perf_counter(),
                            "wall": time.time(),
                            "kind": "sink_detached",
                            "thread": threading.current_thread().name,
                            "error": f"{type(exc).__name__}: {exc}"[:200],
                            "seq": event["seq"],
                        }
                    )
                except Exception:  # noqa: BLE001 — the sink really is dead
                    pass
        return event


class JsonlSink:
    """Append-one-line-per-event trace file, flushed per event so a killed
    process leaves every completed event parseable on disk."""

    def __init__(self, path: str):
        self.path = path
        self._lock = threading.Lock()
        self._f = open(path, "a", encoding="utf-8")

    def emit(self, event: dict[str, Any]) -> None:
        line = json.dumps(event, default=jsonable, separators=(",", ":"))
        with self._lock:
            if self._f.closed:
                return
            self._f.write(line + "\n")
            self._f.flush()

    def close(self) -> None:
        with self._lock:
            if not self._f.closed:
                self._f.flush()
                self._f.close()


class MemorySink:
    """Test sink: collects events in memory."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._events: list[dict[str, Any]] = []

    def emit(self, event: dict[str, Any]) -> None:
        with self._lock:
            self._events.append(event)

    def close(self) -> None:
        pass

    @property
    def events(self) -> list[dict[str, Any]]:
        with self._lock:
            return list(self._events)

    def kinds(self) -> list[str]:
        return [e["kind"] for e in self.events]

    def of_kind(self, kind: str) -> list[dict[str, Any]]:
        return [e for e in self.events if e["kind"] == kind]


class Aggregates:
    """Run-scoped counters, gauges and histograms, summarized at run end.

    Since ISSUE 11 the histograms are O(bins) streaming instruments
    (:class:`obs.metrics.StreamingHistogram`) instead of retained sample
    lists: count/sum/min/max/mean stay exact over a soak-length run,
    quantiles are correct to within one geometric bin, and memory never
    grows with the event count (pinned by the 10^6-event regression test
    in tests/test_slo_metrics.py)."""

    def __init__(self) -> None:
        from page_rank_and_tfidf_using_apache_spark_tpu.obs.metrics import (
            StreamingHistogram,
        )

        self._make_hist = StreamingHistogram
        self._lock = threading.Lock()
        self._counters: dict[str, float] = {}
        self._gauges: dict[str, float] = {}
        self._hists: dict[str, Any] = {}

    def counter(self, name: str, n: float = 1) -> None:
        with self._lock:
            self._counters[name] = self._counters.get(name, 0) + n

    def gauge(self, name: str, value: float) -> None:
        with self._lock:
            self._gauges[name] = float(value)

    def histogram(self, name: str, value: float) -> None:
        with self._lock:
            hist = self._hists.get(name)
            if hist is None:
                hist = self._hists[name] = self._make_hist()
        hist.observe(float(value))

    def summary(self) -> dict[str, Any]:
        with self._lock:
            hists = {name: h.snapshot() for name, h in self._hists.items()}
            return {
                "counters": dict(self._counters),
                "gauges": dict(self._gauges),
                "histograms": hists,
            }


SinkFactory = Callable[[str], Any]
