"""Run manifest: the who/where/how header every traced run writes at
startup and finalizes at exit.

Reference counterpart: the Spark application page — app id, executors,
resolved ``SparkConf``.  Here the manifest records the backend and device
topology (when jax is already imported — writing a manifest never forces
the jax import chain in), the resolved value of every declared ``GRAFT_*``
knob (``utils/config.GRAFT_ENV_KNOBS`` — the same registry the
``env-knob-drift`` lint rule enforces), the git sha, and run identity.
Jax-free processes (the bench parent) never import this package; they
read finished manifests through the stdlib-only ``tools/trace_report.py``.

The startup write is atomic (tmp + rename) and self-sufficient: a child
that is later SIGKILLed still leaves ``status: "running"`` plus its full
environment snapshot — evidence, not a mystery.  ``finalize`` rewrites the
file with the end state (status, wall seconds, event count, the
counter/gauge/histogram summary).
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import tempfile
import time
from typing import Any


def _git_sha() -> str | None:
    root = os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
    try:
        proc = subprocess.run(
            ["git", "-C", root, "rev-parse", "--short", "HEAD"],
            capture_output=True, text=True, timeout=5,
        )
    except (OSError, subprocess.TimeoutExpired):
        return None
    return proc.stdout.strip() or None if proc.returncode == 0 else None


def _device_snapshot() -> dict[str, Any]:
    """Backend + topology, only when jax is already in the process — the
    manifest write itself must never be what pulls the jax import chain
    in (e.g. a run started before the driver's first lazy jax import)."""
    if "jax" not in sys.modules:
        return {"backend": None, "devices": None, "device_count": None}
    try:
        import jax

        devs = jax.devices()
        return {
            "backend": jax.default_backend(),
            "devices": [str(d) for d in devs],
            "device_count": len(devs),
        }
    except Exception as exc:  # noqa: BLE001 — a dead backend is itself evidence
        return {
            "backend": f"error:{type(exc).__name__}",
            "devices": None,
            "device_count": None,
        }


def knob_snapshot() -> dict[str, str | None]:
    """Resolved value (or None) of every declared GRAFT_* knob."""
    from page_rank_and_tfidf_using_apache_spark_tpu.utils.config import (
        GRAFT_ENV_KNOBS,
    )

    return {k: os.environ.get(k) for k in sorted(GRAFT_ENV_KNOBS)}


def _tuned_profile_snapshot() -> dict[str, Any] | None:
    """Provenance of the tuned profile this run resolved knobs from, or
    None when no profile applies — a run manifest must say which tuned
    values shaped it (the ``profile-drift`` tier-3 check audits committed
    profiles; this records what a *specific run* actually saw).  A broken
    or wrong-backend profile is itself evidence: record the error instead
    of raising inside manifest writing."""
    from page_rank_and_tfidf_using_apache_spark_tpu.utils.artifacts import (
        ProvenanceError,
    )
    from page_rank_and_tfidf_using_apache_spark_tpu.utils.config import (
        TunedProfileError,
        load_tuned_profile,
    )

    try:
        prof = load_tuned_profile()
    except (TunedProfileError, ProvenanceError) as exc:
        return {"error": f"{type(exc).__name__}: {exc}"}
    if prof is None:
        return None
    return {
        "path": str(prof.path) if prof.path is not None else None,
        "backend": prof.backend,
        "git_sha": prof.git_sha,
        "source": prof.source,
        "knobs": dict(prof.knobs),
    }


def _atomic_write(path: str, doc: dict[str, Any]) -> None:
    d = os.path.dirname(os.path.abspath(path))
    fd, tmp = tempfile.mkstemp(dir=d, suffix=".tmp")
    try:
        with os.fdopen(fd, "w") as f:
            json.dump(doc, f, indent=2, default=str)
            f.write("\n")
        os.replace(tmp, path)
    finally:
        if os.path.exists(tmp):
            os.unlink(tmp)


def write_manifest(
    path: str,
    name: str,
    trace_path: str | None,
    extra: dict[str, Any] | None = None,
) -> dict[str, Any]:
    """Write the startup manifest; returns the document."""
    doc: dict[str, Any] = {
        "name": name,
        "status": "running",
        "pid": os.getpid(),
        "argv": list(sys.argv),
        "python": sys.version.split()[0],
        "started_wall": time.time(),
        "trace_path": trace_path,
        "git_sha": _git_sha(),
        "lint_clean": None,  # filled by callers that ran the gate (bench.py)
        "knobs": knob_snapshot(),
        "tuned_profile": _tuned_profile_snapshot(),
    }
    doc.update(_device_snapshot())
    if extra:
        doc.update(extra)
    _atomic_write(path, doc)
    return doc


def finalize_manifest(
    path: str,
    doc: dict[str, Any],
    *,
    status: str,
    events: int,
    summary: dict[str, Any] | None = None,
    extra: dict[str, Any] | None = None,
) -> dict[str, Any]:
    """Rewrite the manifest with the run's end state."""
    doc = dict(doc)
    doc["status"] = status
    doc["finished_wall"] = time.time()
    doc["wall_secs"] = doc["finished_wall"] - doc["started_wall"]
    doc["events"] = events
    if summary is not None:
        doc["summary"] = summary
    # the backend may only have resolved after startup (lazy jax import)
    if doc.get("backend") is None:
        doc.update(_device_snapshot())
    if extra:
        doc.update(extra)
    _atomic_write(path, doc)
    return doc
