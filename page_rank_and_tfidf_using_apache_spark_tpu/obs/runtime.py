"""Run lifecycle and the process-global telemetry entry points.

One process has ONE event bus and ONE span tracer (module globals here);
``obs.emit`` / ``obs.span`` work from anywhere — resilience retries, the
streaming tokenizer thread, checkpoint writes — whether or not a run is
active.  With no run, events fan out to whatever sinks tests attached and
aggregation is a no-op, so instrumented library code costs nothing.

:func:`start_run` turns the stream into durable artifacts: it resolves a
trace directory (explicit argument, else the ``GRAFT_TRACE_DIR`` env knob),
opens the crash-safe JSONL sink at ``<dir>/<name>.<pid>.trace.jsonl``,
writes the startup manifest next to it, and publishes ``run_start``.
:func:`end_run` publishes ``run_end`` carrying the counter/gauge/histogram
summary and finalizes the manifest.  An ``atexit`` hook finalizes a run the
caller forgot (status ``"atexit"``); only SIGKILL leaves ``"running"`` —
which is precisely the durable evidence of *where* it died.
"""

from __future__ import annotations

import atexit
import contextlib
import os
import threading
from typing import Any, Iterator

from page_rank_and_tfidf_using_apache_spark_tpu.obs import manifest as mf
from page_rank_and_tfidf_using_apache_spark_tpu.obs.events import (
    Aggregates,
    EventBus,
    JsonlSink,
)
from page_rank_and_tfidf_using_apache_spark_tpu.obs.trace import SpanTracer

_BUS = EventBus()
_TRACER = SpanTracer(_BUS)

_run_lock = threading.Lock()
_active_run: "Run | None" = None
_atexit_registered = False


class Run:
    """One traced run: JSONL sink + manifest + aggregates."""

    def __init__(self, name: str, trace_dir: str | None):
        self.name = name
        self.aggregates = Aggregates()
        self.trace_path: str | None = None
        self.manifest_path: str | None = None
        self._manifest_doc: dict[str, Any] | None = None
        self._sink: JsonlSink | None = None
        self._events0 = 0
        self._finalized = False
        # Cross-process span propagation (ROADMAP hardening (c)): a parent
        # process that wants one trace tree over many children exports an
        # opaque trace id as GRAFT_TRACE_PARENT; every child run adopts it
        # here — in the run_start event AND the manifest — so
        # tools/trace_report.py --stitch can reassemble the round's tree
        # from the artifacts alone, no pid archaeology.
        self.trace_parent = os.environ.get("GRAFT_TRACE_PARENT") or None
        if trace_dir:
            os.makedirs(trace_dir, exist_ok=True)
            stem = f"{name}.{os.getpid()}"
            self.trace_path = os.path.join(trace_dir, f"{stem}.trace.jsonl")
            self.manifest_path = os.path.join(trace_dir, f"{stem}.manifest.json")
            # manifest first, sink last: the failure-prone steps (atomic
            # manifest write, trace-file open) run before anything attaches
            # to the bus, so a failed construction can never leak an
            # attached orphan sink collecting a run that never started
            self._manifest_doc = mf.write_manifest(
                self.manifest_path, name, self.trace_path,
                extra=(
                    {"trace_parent": self.trace_parent}
                    if self.trace_parent else None
                ),
            )
            self._sink = JsonlSink(self.trace_path)
            _BUS.attach(self._sink)
        start = _BUS.publish(
            "run_start", name=name, run_pid=os.getpid(),
            **({"trace_parent": self.trace_parent} if self.trace_parent else {}),
        )
        self._events0 = start["seq"]

    # ------------------------------------------------------------- metrics

    def counter(self, name: str, n: float = 1) -> None:
        self.aggregates.counter(name, n)

    def gauge(self, name: str, value: float) -> None:
        self.aggregates.gauge(name, value)

    def histogram(self, name: str, value: float) -> None:
        self.aggregates.histogram(name, value)

    # ------------------------------------------------------------ lifecycle

    def finalize(self, status: str = "ok", extra: dict[str, Any] | None = None) -> None:
        if self._finalized:
            return
        self._finalized = True
        summary = self.aggregates.summary()
        end = _BUS.publish("run_end", name=self.name, status=status, summary=summary)
        if self._sink is not None:
            _BUS.detach(self._sink)
            self._sink.close()
        if self.manifest_path and self._manifest_doc is not None:
            mf.finalize_manifest(
                self.manifest_path,
                self._manifest_doc,
                status=status,
                events=end["seq"] - self._events0 + 1,
                summary=summary,
                extra=extra,
            )


# ---------------------------------------------------------------- module API


def bus() -> EventBus:
    return _BUS


def tracer() -> SpanTracer:
    return _TRACER


def emit(kind: str, /, **fields: Any) -> dict[str, Any]:
    """Publish one event on the process bus."""
    return _BUS.publish(kind, **fields)


def span(name: str, /, *, parent: int | None = None, **attrs: Any):
    """Open a traced span (context manager; see obs/trace.py)."""
    return _TRACER.span(name, parent=parent, **attrs)


def current_run() -> Run | None:
    with _run_lock:
        return _active_run


def counter(name: str, n: float = 1) -> None:
    run = current_run()
    if run is not None:
        run.counter(name, n)


def gauge(name: str, value: float) -> None:
    run = current_run()
    if run is not None:
        run.gauge(name, value)


def histogram(name: str, value: float) -> None:
    run = current_run()
    if run is not None:
        run.histogram(name, value)


def _finalize_leftover() -> None:
    run = current_run()
    if run is not None:
        end_run(status="atexit")


def start_run(name: str, trace_dir: str | None = None) -> Run:
    """Begin a traced run.  ``trace_dir`` defaults to the GRAFT_TRACE_DIR
    env knob; with neither, the run has no JSONL sink or manifest (events
    still reach any attached sinks, aggregates still fold).  Starting a
    run while one is active finalizes the old one first (status
    ``"superseded"``) — runs never nest."""
    global _active_run, _atexit_registered
    if trace_dir is None:
        trace_dir = os.environ.get("GRAFT_TRACE_DIR") or None
    prev = current_run()
    if prev is not None:
        prev.finalize(status="superseded")
    run = Run(name, trace_dir)
    with _run_lock:
        _active_run = run
        if not _atexit_registered:
            atexit.register(_finalize_leftover)
            _atexit_registered = True
    return run


def end_run(status: str = "ok", extra: dict[str, Any] | None = None) -> None:
    """Finalize and clear the active run (no-op when none is active)."""
    global _active_run
    with _run_lock:
        run, _active_run = _active_run, None
    if run is not None:
        run.finalize(status=status, extra=extra)


@contextlib.contextmanager
def run(name: str, trace_dir: str | None = None) -> Iterator[Run]:
    """``with obs.run("tfidf"):`` — start_run/end_run with error status
    propagation (an exception finalizes as ``error:<Type>`` and re-raises)."""
    r = start_run(name, trace_dir)
    try:
        yield r
    except BaseException as exc:
        end_run(status=f"error:{type(exc).__name__}")
        raise
    else:
        end_run(status="ok")
