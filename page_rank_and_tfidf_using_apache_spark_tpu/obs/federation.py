"""Fleet metrics federation: one observability plane over N replica
processes (ISSUE 19).

Reference counterpart: the Spark driver's metrics system — executors
report to the driver's sink, and both the UI and dynamic allocation read
the *aggregate*, not per-executor boards.  PR 17's serving fabric left
each replica's :class:`obs.metrics.MetricsHub` private behind its own
exporter; this module closes the gap:

- :class:`FleetHub` scrapes each registered replica's existing
  ``/snapshot.json`` on a background scraper thread (``fed-scraper``).
  Every scrape is ONE guarded attempt at the ``fed_scrape`` site
  (:func:`resilience.executor.attempt_once` with a hard deadline), so
  injected partitions/hangs surface as scrape failures on this thread —
  never as backpressure on routing.
- Replica state merges *exactly*: the scrape reads the ``mergeable``
  section every hub snapshot now embeds and folds it into a fresh fleet
  :class:`MetricsHub` per read (counts/sums/min/max byte-exact vs a hub
  fed the union stream; quantiles within one bin).
- A replica that stops answering is marked **stale** — its age since
  the last good scrape is tracked, exported in the fleet snapshot and
  as a ``replica=``-labeled gauge — and its last-known state stays in
  the aggregate.  Partitioned replicas are never silently dropped, and
  the scraper never blocks the router's query path.

:class:`FleetHub` duck-types the hub surface :class:`obs.export.MetricsExporter`
serves (``snapshot()`` / ``prometheus()``), so the router publishes the
fleet board from its own ``/snapshot.json`` + ``/metrics`` with one
exporter and zero new endpoint code; ``/metrics`` carries per-replica
breakdown rows beside the fleet aggregate.
"""

from __future__ import annotations

import json
import os
import threading
import time
import urllib.request
from typing import Any, Callable

from page_rank_and_tfidf_using_apache_spark_tpu.obs import runtime as _rt
from page_rank_and_tfidf_using_apache_spark_tpu.obs.metrics import MetricsHub


def _rx():
    """The resilience executor, imported lazily: this module loads during
    ``obs`` package init, and ``resilience`` -> ``utils.metrics`` -> ``obs``
    would close an import cycle at that moment.  First scrape pays the
    import; every later call is a dict hit."""
    from page_rank_and_tfidf_using_apache_spark_tpu.resilience import (
        executor,
    )

    return executor

# The guarded scrape site: chaos specs (net_partition/net_hang) aim here,
# and the watchdog deadline bounds a hung scrape to the scrape timeout.
FED_SCRAPE_SITE = "fed_scrape"

_DEFAULT_SCRAPE_S = 1.0


def scrape_period_from_env() -> float:
    """The GRAFT_FED_SCRAPE_S knob: seconds between fleet scrapes
    (default 1.0)."""
    raw = os.environ.get("GRAFT_FED_SCRAPE_S")
    if raw is None or raw.strip() == "":
        return _DEFAULT_SCRAPE_S
    return float(raw)


def _prom_name(raw: str) -> str:
    return "graft_" + "".join(
        c if (c.isalnum() or c == "_") else "_" for c in raw
    )


class FleetHub:
    """Scrape-and-merge federation over replica metrics endpoints.

    ``register(replica, url)`` / ``deregister(replica)`` track the live
    fleet (the fabric calls these as replicas spawn and drain); the
    scraper thread pulls each target's ``/snapshot.json`` every
    ``scrape_s`` seconds.  ``snapshot()`` rebuilds a fresh fleet
    :class:`MetricsHub` from the latest per-replica mergeables on every
    read — re-merging fresh scrapes instead of accumulating into a
    long-lived hub is what keeps the merge one-shot-exact (no
    double-counting across scrape cycles)."""

    def __init__(self, *, window_s: float = 60.0, slots: int = 30,
                 latency_slo_s: float | None = None,
                 availability_target: float | None = None,
                 scrape_s: float | None = None,
                 stale_after_s: float | None = None,
                 timeout_s: float = 2.0,
                 clock: Callable[[], float] = time.monotonic,
                 fetch: Callable[[str], dict[str, Any]] | None = None):
        self.window_s = float(window_s)
        self.slots = int(slots)
        self.latency_slo_s = latency_slo_s
        self.availability_target = availability_target
        self.scrape_s = float(scrape_s if scrape_s is not None
                              else scrape_period_from_env())
        # stale = three missed scrape periods by default: one lost scrape
        # is jitter, three is a partition.
        self.stale_after_s = float(stale_after_s if stale_after_s is not None
                                   else 3.0 * self.scrape_s)
        self.timeout_s = float(timeout_s)
        self._clock = clock
        self._fetch = fetch or self._http_fetch
        self._lock = threading.Lock()
        self._targets: dict[str, str] = {}
        self._mergeables: dict[str, dict[str, Any]] = {}
        self._replica_snaps: dict[str, dict[str, Any]] = {}
        self._first_seen: dict[str, float] = {}
        self._last_ok: dict[str, float] = {}
        self._scrapes = 0
        self._scrape_errors = 0
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None

    # ------------------------------------------------------------ membership

    def register(self, replica: str, url: str) -> None:
        with self._lock:
            self._targets[str(replica)] = url.rstrip("/")
            self._first_seen.setdefault(str(replica), self._clock())

    def deregister(self, replica: str) -> None:
        """Remove a drained replica from the fleet: its contribution
        leaves the aggregate with it (a *partitioned* replica, by
        contrast, stays registered and is labeled stale)."""
        r = str(replica)
        with self._lock:
            for d in (self._targets, self._mergeables, self._replica_snaps,
                      self._first_seen, self._last_ok):
                d.pop(r, None)

    def replicas(self) -> list[str]:
        with self._lock:
            return sorted(self._targets)

    # -------------------------------------------------------------- scraping

    def _http_fetch(self, url: str) -> dict[str, Any]:
        with urllib.request.urlopen(f"{url}/snapshot.json",
                                    timeout=self.timeout_s) as resp:
            return json.loads(resp.read())

    def scrape_once(self) -> dict[str, bool]:
        """One scrape sweep over the current fleet; returns per-replica
        success.  Each target is one guarded ``fed_scrape`` attempt with
        a hard watchdog deadline — a hung endpoint costs this thread at
        most the timeout and the replica an increased staleness age,
        never a routing stall."""
        with self._lock:
            targets = dict(self._targets)
        ok: dict[str, bool] = {}
        rx = _rx()
        deadline = rx.RetryPolicy(deadline_s=self.timeout_s + 1.0)
        for replica, url in sorted(targets.items()):
            self._scrapes += 1
            try:
                snap = rx.attempt_once(
                    lambda url=url: self._fetch(url),
                    site=FED_SCRAPE_SITE, policy=deadline,
                )
                mergeable = snap.get("mergeable")
                if not isinstance(mergeable, dict):
                    raise ValueError("snapshot has no mergeable section")
            except Exception as exc:  # noqa: BLE001 — any fault = stale, loop on
                self._scrape_errors += 1
                _rt.emit("fed_scrape_error", replica=replica,
                         error=f"{type(exc).__name__}: {exc}"[:200])
                ok[replica] = False
                continue
            with self._lock:
                if replica in self._targets:  # lost a churn race: drop it
                    self._mergeables[replica] = mergeable
                    self._replica_snaps[replica] = snap
                    self._last_ok[replica] = self._clock()
            ok[replica] = True
        return ok

    def _scrape_loop(self) -> None:
        while not self._stop.is_set():
            self.scrape_once()
            self._stop.wait(self.scrape_s)

    def start(self) -> "FleetHub":
        if self._thread is None:
            self._stop.clear()
            self._thread = threading.Thread(
                target=self._scrape_loop, name="fed-scraper", daemon=True)
            self._thread.start()
            _rt.emit("fed_start", scrape_s=self.scrape_s,
                     stale_after_s=self.stale_after_s)
        return self

    def stop(self) -> None:
        self._stop.set()
        t, self._thread = self._thread, None
        if t is not None:
            t.join(timeout=5.0)

    def __enter__(self) -> "FleetHub":
        return self.start()

    def __exit__(self, *exc: object) -> None:
        self.stop()

    # ------------------------------------------------------------- staleness

    def staleness(self) -> dict[str, float]:
        """Seconds since each registered replica's last good scrape (age
        since registration while it has never answered)."""
        now = self._clock()
        with self._lock:
            return {
                r: now - self._last_ok.get(r, self._first_seen.get(r, now))
                for r in self._targets
            }

    # ----------------------------------------------------- the fleet board

    def _merged_hub(self) -> tuple[MetricsHub, dict[str, str]]:
        """A fresh hub holding the exact fold of every replica's latest
        mergeable.  Per-replica merge failures (layout drift from a
        mixed-version fleet) are recorded, not raised — one bad replica
        must not take down the router's snapshot endpoint."""
        with self._lock:
            members = sorted(self._targets)
            mergeables = {r: self._mergeables.get(r) for r in members}
        hub = MetricsHub(window_s=self.window_s, slots=self.slots,
                         latency_slo_s=self.latency_slo_s,
                         availability_target=self.availability_target,
                         clock=self._clock)
        errors: dict[str, str] = {}
        for r in members:
            m = mergeables.get(r)
            if m is None:
                continue  # registered but never scraped: stale, zero data
            try:
                hub.merge_mergeable(m)
            except Exception as exc:  # noqa: BLE001 — recorded, never fatal
                errors[r] = f"{type(exc).__name__}: {exc}"[:200]
        return hub, errors

    def snapshot(self) -> dict[str, Any]:
        """The fleet snapshot the router's ``/snapshot.json`` serves: a
        full merged-hub snapshot plus a ``fleet`` section with
        membership, per-replica staleness ages, the stale set, and a
        per-replica board (latency/requests/errors) for breakdown rows."""
        hub, merge_errors = self._merged_hub()
        ages = self.staleness()
        stale = sorted(r for r, age in ages.items()
                       if age > self.stale_after_s)
        hub.gauge("fed_replicas", float(len(ages)))
        hub.gauge("fed_stale_replicas", float(len(stale)))
        hub.gauge("fed_staleness_s_max",
                  round(max(ages.values()), 3) if ages else 0.0)
        snap = hub.snapshot()
        with self._lock:
            replica_snaps = dict(self._replica_snaps)
        per_replica: dict[str, Any] = {}
        for r in sorted(ages):
            rs = replica_snaps.get(r) or {}
            win = (rs.get("latency_s") or {}).get("window") or {}
            ctr = rs.get("counters") or {}
            per_replica[r] = {
                "stale": r in stale,
                "staleness_s": round(ages[r], 3),
                "p50_s": win.get("p50"),
                "p99_s": win.get("p99"),
                "requests": (ctr.get("serve.requests") or {}).get("total", 0),
                "errors": (ctr.get("serve.errors") or {}).get("total", 0),
            }
        snap["fleet"] = {
            "replicas": sorted(ages),
            "stale": stale,
            "stale_after_s": self.stale_after_s,
            "scrape_s": self.scrape_s,
            "staleness_s": {r: round(a, 3) for r, a in sorted(ages.items())},
            "scrapes": self._scrapes,
            "scrape_errors": self._scrape_errors,
            "merge_errors": merge_errors,
            "per_replica": per_replica,
        }
        return snap

    def prometheus(self) -> str:
        """The merged hub's exposition plus ``replica=``-labeled
        breakdown rows (per-replica quantiles, counters, staleness) so
        one scrape of the router shows the fleet AND its members."""
        hub, _ = self._merged_hub()
        ages = self.staleness()
        hub.gauge("fed_replicas", float(len(ages)))
        hub.gauge("fed_staleness_s_max",
                  round(max(ages.values()), 3) if ages else 0.0)
        lines = [hub.prometheus().rstrip("\n")]
        with self._lock:
            replica_snaps = dict(self._replica_snaps)
        for r in sorted(ages):
            lines.append(
                f'graft_fed_staleness_seconds{{replica="{r}"}} '
                f"{ages[r]:.6g}"
            )
            rs = replica_snaps.get(r)
            if not rs:
                continue
            win = (rs.get("latency_s") or {}).get("window") or {}
            for q in ("p50", "p90", "p95", "p99"):
                v = win.get(q)
                if v is not None:
                    lines.append(
                        f'graft_serve_latency_seconds{{window="rolling",'
                        f'quantile="0.{q[1:]}",replica="{r}"}} {v:.6g}'
                    )
            for name, c in (rs.get("counters") or {}).items():
                lines.append(
                    f'{_prom_name(name)}_total{{replica="{r}"}} '
                    f"{float(c.get('total', 0)):.6g}"
                )
        return "\n".join(lines) + "\n"
